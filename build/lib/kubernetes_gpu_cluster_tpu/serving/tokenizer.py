"""Tokenization for the serving layer: text in / text out.

The engine works purely in token ids; the server layer owns the tokenizer and
string-level stop handling (the contract stated at engine/sequence.py: stop
STRINGS are evaluated here, stop TOKEN ids in the engine). The reference's
user contract is an OpenAI API over text (reference ``old_README.md:1472-1476``);
its models shipped with HF tokenizer files pre-staged on every node
(``old_README.md:1482-1561``) — mirrored here by ``load_tokenizer`` accepting a
local path.

Two implementations:

- ``HFTokenizer``: wraps a ``transformers`` AutoTokenizer loaded from a local
  directory (zero-egress environments cannot download; deployment pre-stages
  files the way the reference staged /models).
- ``ByteTokenizer``: self-contained UTF-8 byte-level tokenizer (no files).
  Used for debug models, tests, and as the guaranteed-available fallback.

``IncrementalDetokenizer`` turns a stream of token ids into a stream of text
deltas with stop-string scanning: emitted text is held back by the longest
stop-string prefix that could still complete, so a stop string split across
window boundaries is never leaked to the client.
"""

from __future__ import annotations

from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    eos_token_id: Optional[int]

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted by 3 (0=pad, 1=bos, 2=eos). vocab_size=259."""

    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 3

    def __init__(self, add_bos: bool = True):
        self.add_bos = add_bos
        self.eos_token_id = self.EOS
        self.vocab_size = 256 + self.OFFSET

    def encode(self, text: str) -> list[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return [self.BOS] + ids if self.add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(t - self.OFFSET for t in ids
                     if self.OFFSET <= t < 256 + self.OFFSET)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers AutoTokenizer wrapper (local files only in this env)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.eos_token_id = self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        return self._tok.apply_chat_template(
            messages, tokenize=False, add_generation_prompt=True)


def load_tokenizer(name_or_path: Optional[str]) -> Tokenizer:
    """Resolve a tokenizer: a local path -> HFTokenizer; None or "byte" ->
    ByteTokenizer (debug models / tests / no staged files)."""
    if name_or_path in (None, "byte", "bytes"):
        return ByteTokenizer()
    return HFTokenizer(name_or_path)


def apply_chat_template(tokenizer: Tokenizer, messages: list[dict]) -> str:
    """Chat-messages -> prompt string. Uses the model's own template when the
    tokenizer ships one; otherwise a minimal role-tagged fallback."""
    fn = getattr(tokenizer, "apply_chat_template", None)
    if fn is not None:
        try:
            return fn(messages)
        except Exception:
            pass
    parts = [f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}"
             for m in messages]
    return "\n".join(parts) + "\n<|assistant|>\n"


class IncrementalDetokenizer:
    """Token-id stream -> text-delta stream with stop-string handling.

    decode() is re-run over the full output ids each push and diffed against
    the previously emitted prefix — O(n) per call in output length, robust to
    tokenizers whose token boundaries do not align with character boundaries
    (UTF-8 multibyte, BPE merges).
    """

    def __init__(self, tokenizer: Tokenizer, stop: Sequence[str] = ()):
        self.tokenizer = tokenizer
        self.stop = [s for s in stop if s]
        self._ids: list[int] = []
        self._emitted = 0          # chars of decoded text already released
        self._stopped = False
        # Max chars that must be held back so a partially-matched stop string
        # can still complete: longest stop minus 1.
        self._holdback = max((len(s) for s in self.stop), default=1) - 1

    @property
    def stopped(self) -> bool:
        return self._stopped

    @property
    def text(self) -> str:
        return self.tokenizer.decode(self._ids)

    def push(self, ids: Sequence[int], final: bool = False) -> str:
        """Feed new token ids; returns the text delta safe to emit now.
        After a stop string matches, the delta ends right before the stop
        string and ``stopped`` flips — callers should abort the request."""
        if self._stopped:
            return ""
        self._ids.extend(ids)
        text = self.tokenizer.decode(self._ids)
        for s in self.stop:
            # Scan from just before the emitted point: the stop string may
            # straddle the emitted/held-back boundary.
            start = max(0, self._emitted - len(s) + 1)
            idx = text.find(s, start)
            if idx != -1:
                self._stopped = True
                delta = text[self._emitted:idx]
                self._emitted = idx
                return delta
        limit = len(text) if final else max(self._emitted,
                                            len(text) - self._holdback)
        # A partial UTF-8 sequence at the stream end decodes to U+FFFD and
        # would be rewritten once the next token completes it — hold it back.
        while limit > self._emitted and not final and text[limit - 1] == "�":
            limit -= 1
        delta = text[self._emitted:limit]
        self._emitted = limit
        return delta
