"""Prometheus-format serving metrics (/metrics endpoint).

The reference had NO metrics surface at all — observability was kubectl
transcripts (SURVEY §5 "Metrics/logging/observability: no Prometheus/
Grafana") — so this is framework-over-reference functionality the north star
asks for: tok/s, TTFT p50/p95 under continuous batching, preemptions, KV page
occupancy.

Counters come from engine.EngineStats (filled inside the step loop) and
scheduler/allocator state; this module only formats. Text format per the
Prometheus exposition spec — scrapeable without any client library.
"""

from __future__ import annotations

import time


class Metrics:
    def __init__(self, engine):
        self.engine = engine               # LLMEngine
        self.requests_total = 0
        self.responses_total = 0
        self.response_tokens_total = 0
        self._started = time.monotonic()

    # -- hooks called by the API layer --------------------------------------

    def on_request(self) -> None:
        self.requests_total += 1

    def on_finish(self, n_tokens: int) -> None:
        """HTTP-layer completion: counts responses actually delivered to
        clients (engine-side requests_finished also covers aborts/terminated
        sequences, so the two legitimately differ under churn)."""
        self.responses_total += 1
        self.response_tokens_total += n_tokens

    # -- rendering ----------------------------------------------------------

    def render(self) -> str:
        eng = self.engine
        stats = eng.stats
        sched = eng.scheduler
        alloc = sched.allocator
        q = stats.quantile
        lines = [
            "# TYPE kgct_requests_total counter",
            f"kgct_requests_total {self.requests_total}",
            "# TYPE kgct_responses_total counter",
            f"kgct_responses_total {self.responses_total}",
            "# TYPE kgct_response_tokens_total counter",
            f"kgct_response_tokens_total {self.response_tokens_total}",
            "# TYPE kgct_requests_finished_total counter",
            f"kgct_requests_finished_total {stats.requests_finished}",
            "# TYPE kgct_tokens_generated_total counter",
            f"kgct_tokens_generated_total {stats.tokens_generated}",
            "# TYPE kgct_prefill_tokens_total counter",
            f"kgct_prefill_tokens_total {stats.prefill_tokens}",
            "# TYPE kgct_engine_steps_total counter",
            f"kgct_engine_steps_total {stats.steps}",
            "# TYPE kgct_preemptions_total counter",
            f"kgct_preemptions_total {sched.num_preemptions}",
            "# TYPE kgct_num_waiting gauge",
            f"kgct_num_waiting {len(sched.waiting)}",
            "# TYPE kgct_num_running gauge",
            f"kgct_num_running {len(sched.running)}",
            "# TYPE kgct_kv_pages_total gauge",
            f"kgct_kv_pages_total {alloc.num_pages}",
            "# TYPE kgct_kv_pages_free gauge",
            f"kgct_kv_pages_free {alloc.num_free}",
            "# TYPE kgct_ttft_seconds summary",
            f'kgct_ttft_seconds{{quantile="0.5"}} {q(stats.ttft_s, 0.5)}',
            f'kgct_ttft_seconds{{quantile="0.95"}} {q(stats.ttft_s, 0.95)}',
            "# TYPE kgct_step_seconds summary",
            f'kgct_step_seconds{{quantile="0.5"}} {q(stats.step_s, 0.5)}',
            f'kgct_step_seconds{{quantile="0.95"}} {q(stats.step_s, 0.95)}',
            "# TYPE kgct_uptime_seconds gauge",
            f"kgct_uptime_seconds {time.monotonic() - self._started:.1f}",
        ]
        return "\n".join(lines) + "\n"
