"""Serving layer: tokenizer, async engine, OpenAI-compatible API, router.

The user-facing surface the reference delivered via the vLLM Helm chart's
router + engine pods (reference ``old_README.md:1472-1476``), native here.
"""

from .async_engine import AsyncLLMEngine, StreamChunk
from .tokenizer import (ByteTokenizer, HFTokenizer, IncrementalDetokenizer,
                        apply_chat_template, load_tokenizer)

__all__ = [
    "AsyncLLMEngine", "StreamChunk", "ByteTokenizer", "HFTokenizer",
    "IncrementalDetokenizer", "apply_chat_template", "load_tokenizer",
]
