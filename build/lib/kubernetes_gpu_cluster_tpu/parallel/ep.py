"""Expert parallelism (EP) for the mixtral-class MoE block.

The reference had no MoE at all (SURVEY §2 "EP: absent — must build"); the
north-star configs require Mixtral-8x7B expert-parallel across a slice
(BASELINE.json config 4). Two composable mechanisms provide it:

1. **GSPMD path** (the engine default): expert weights carry
   ``P(None, "ep", None, "tp")`` shardings (parallel/sharding.py) and the
   dense-dispatch combine einsum in models/llama._moe_mlp contracts the expert
   axis, so the SPMD partitioner turns it into local-expert compute + a psum
   over ``ep`` riding ICI. No dispatch/combine all-to-alls: with the serving
   hot loop's small token counts, dense dispatch is MXU-bound and avoids the
   ragged all-to-all entirely.

2. **Manual path** (inside the PP shard_map): ``_moe_mlp(ep_axis="ep")``
   slices the combine weights to the local expert shard and psums explicitly
   (see parallel/pp.py).

This module exposes the manual block standalone — used by tests to pin down
EP semantics against the single-device oracle, and the building block a future
ragged all-to-all dispatch (large-prefill optimization) will slot into.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import ModelConfig
from ..models.llama import _moe_mlp


def moe_block_ep(mesh: Mesh, cfg: ModelConfig, layer_params: dict, x: jax.Array):
    """Run one MoE block with experts sharded over the mesh's ``ep`` axis
    (and per-expert ffn over ``tp``) via shard_map. ``layer_params`` holds one
    layer's ``router``/``w_gate``/``w_up``/``w_down`` (no leading L axis).
    x: [T, d] replicated."""
    if cfg.num_experts % mesh.shape["ep"] != 0:
        raise ValueError(f"num_experts={cfg.num_experts} not divisible by "
                         f"ep={mesh.shape['ep']}")
    in_specs = ({"router": P(),
                 "w_gate": P("ep", None, "tp"),
                 "w_up": P("ep", None, "tp"),
                 "w_down": P("ep", "tp", None)}, P())

    def local_fn(lp, x):
        return _moe_mlp(lp, x, cfg, tp_axis="tp", ep_axis="ep")

    return jax.shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                         out_specs=P(), check_vma=False)(layer_params, x)
