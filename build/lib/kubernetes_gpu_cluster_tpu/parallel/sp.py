"""Sequence/context parallelism: ring attention over the ``sp`` mesh axis.

Long-context prefill is where a single chip runs out of road first: attention
is O(T^2) FLOPs and the KV for one long prompt is O(T) HBM. The reference had
NO answer here — it only CAPPED context (``--max-model-len`` 128-4096,
reference ``values-01-minimal-example6.yaml:19-20``, ``...8.yaml:27``) because
vLLM/NCCL had no sequence-parallel path it could configure. This module is
framework-over-reference capability, TPU-first by construction:

- the sequence axis is sharded over ``sp``: each device holds ``T/sp`` query
  tokens and the matching K/V shard;
- K/V/metadata blocks rotate around the ring with ``lax.ppermute`` (one ICI
  neighbor hop per step — the mesh places ``sp`` adjacent to ``tp`` so hops
  stay on-slice), overlapping each hop with the local block's attention
  compute;
- softmax is accumulated online (flash-style m/l/acc carries in fp32), so no
  device ever materializes a [T, T] score matrix — peak memory per device is
  O((T/sp)^2) scores + O(T/sp) KV;
- causal + segment masking works on GLOBAL positions/segment ids, which
  travel with their K/V block, so ragged multi-sequence prefill batches work
  exactly like ops/attention.ragged_prefill_attention.

This is the blockwise/ring formulation of Liu et al.'s Ring Attention
(arXiv:2310.01889) specialized to causal ragged serving prefill.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

NEG = jnp.float32(-1e30)


def _block_attend(qg, k_blk, v_blk, q_seg, k_seg, q_pos, k_pos,
                  m, l, acc, scale):
    """One ring step: local queries against one rotating K/V block, online-
    softmax accumulated. qg: [Tl, n_kv, g, hd]; k_blk/v_blk: [Tb, n_kv, hd];
    m/l: [Tl, n_kv, g, 1]; acc: [Tl, n_kv, g, hd]; all fp32."""
    scores = jnp.einsum("tkgh,skh->tkgs", qg * scale, k_blk)  # [Tl,n_kv,g,Tb]
    mask = ((q_seg[:, None] == k_seg[None, :]) & (q_seg[:, None] >= 0)
            & (q_pos[:, None] >= k_pos[None, :]))             # [Tl, Tb]
    scores = jnp.where(mask[:, None, None, :], scores, NEG)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    p = jnp.where(mask[:, None, None, :], p, 0.0)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum("tkgs,skh->tkgh", p, v_blk)
    return m_new, l, acc


def _ring_body(q, k, v, seg_ids, positions, *, scale, axis, n_kv, q_per_kv):
    """shard_map body: everything here sees the LOCAL shard and the sp axis."""
    Tl, nh, hd = q.shape
    sp = jax.lax.psum(1, axis)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    qg = q.astype(jnp.float32).reshape(Tl, n_kv, q_per_kv, hd)
    m = jnp.full((Tl, n_kv, q_per_kv, 1), NEG, jnp.float32)
    l = jnp.zeros((Tl, n_kv, q_per_kv, 1), jnp.float32)
    acc = jnp.zeros((Tl, n_kv, q_per_kv, hd), jnp.float32)

    def step(i, carry):
        k_blk, v_blk, k_seg, k_pos, m, l, acc = carry
        m, l, acc = _block_attend(qg, k_blk.astype(jnp.float32),
                                  v_blk.astype(jnp.float32),
                                  seg_ids, k_seg, positions, k_pos,
                                  m, l, acc, scale)
        # Rotate the K/V block (+ its global metadata) one ring hop. The
        # ppermute is issued after compute; XLA overlaps the collective with
        # the next iteration's einsum where the schedule allows.
        k_blk, v_blk, k_seg, k_pos = jax.lax.ppermute(
            (k_blk, v_blk, k_seg, k_pos), axis, perm)
        return k_blk, v_blk, k_seg, k_pos, m, l, acc

    carry = (k, v, seg_ids, positions, m, l, acc)
    *_, m, l, acc = jax.lax.fori_loop(0, sp, step, carry)
    out = acc / jnp.maximum(l, 1e-20)           # fully-masked rows -> 0
    return out.reshape(Tl, nh, hd).astype(q.dtype)


def build_ring_prefill(mesh, num_kv_heads: int, q_per_kv: int, scale: float,
                       axis: str = "sp"):
    """Returns a jitted ragged-prefill attention fn running ring attention
    over ``mesh`` axis ``axis``.

    Signature matches ops.attention.ragged_prefill_attention_xla:
    ``fn(q [T,nh,hd], k [T,n_kv,hd], v, seg_ids [T], positions [T]) ->
    [T,nh,hd]`` with T sharded over the axis (T % axis_size == 0; pad ragged
    tails with seg_id=-1 exactly like the single-chip path).
    """
    body = functools.partial(_ring_body, scale=scale, axis=axis,
                             n_kv=num_kv_heads, q_per_kv=q_per_kv)
    seq = P(axis)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(seq, seq, seq, seq, seq),
        out_specs=seq,
        check_rep=False)

    @jax.jit
    def ring_prefill(q, k, v, seg_ids, positions):
        return mapped(q, k, v, seg_ids, positions)

    return ring_prefill


def sequence_sharding(mesh, axis: str = "sp"):
    """NamedSharding placing a [T, ...] prefill batch over the sp ring."""
    return NamedSharding(mesh, P(axis))
