"""Device-mesh parallelism: TP/EP/PP/DP over ICI and DCN.

This package is the TPU-native replacement for the reference's entire
distributed stack — NCCL allreduce inside vLLM images (reference
``values-01-minimal-example8.yaml:32,53-59``: ``--disable-custom-all-reduce``
plus 10Gi ``/dev/shm``), and Ray/KubeRay for cross-node pipeline parallelism
(reference ``values-01-minimal-example4.yaml:18,42-46``, ``old_README.md:1570-1625``).

Design (SURVEY §2 "Parallelism strategies" obligations):

- **mesh.py** — one `jax.sharding.Mesh` with axes
  ``("dp", "pp", "ep", "sp", "tp")``; TP innermost so it rides ICI, sp next
  so ring hops stay on-slice, DP/PP outermost so they may cross hosts over
  DCN. Multi-host bootstrap via `jax.distributed` with stable-DNS coordinator
  discovery (the JobSet pattern replacing `kubeadm token` ssh plumbing).
- **sharding.py** — GSPMD sharding-by-annotation for TP and EP: params and the
  paged KV pool carry `NamedSharding`s, XLA inserts the all-gathers/psums.
  No hand-written collectives in the hot path.
- **pp.py** — pipeline parallelism as a `shard_map` circular pipeline:
  stacked layer weights sharded over ``pp`` on the layer axis, microbatched
  hidden states rotating stage-to-stage via `lax.ppermute`.
- **ep.py** — expert parallelism helpers for the mixtral-class MoE block.
- **sp.py** — sequence/context parallelism: ring attention over the ``sp``
  axis for long-context prefill (capability the reference lacked entirely —
  it capped context instead, SURVEY §5 "Long-context").
"""

from .mesh import make_mesh, initialize_distributed, mesh_from_config
from .sharding import param_shardings, kv_cache_sharding, data_shardings

__all__ = [
    "make_mesh", "initialize_distributed", "mesh_from_config",
    "param_shardings", "kv_cache_sharding", "data_shardings",
]
