"""Paged KV cache: device-side page pool + host-side page allocator.

The reference relied on vLLM's PagedAttention block manager inside the CUDA
images and only exposed sizing knobs (``gpuMemoryUtilization``, ``maxModelLen``
— reference ``values-01-minimal-example8.yaml:26-27``, SURVEY C29). Here the
paged cache is native:

- Device side: one K and one V array of shape
  ``[num_layers, num_pages, page_size, num_kv_heads * head_dim]`` living in
  HBM. Layout rationale (TPU): the head dims are stored FLATTENED so the last
  (lane) dimension is >=128-aligned — Mosaic requires DMA slices aligned to
  the 128-lane tiling, and head_dim=64 models would violate it unflattened.
  A page slice ``[page_size, n_kv*hd]`` is the DMA unit the Pallas decode
  kernel streams HBM->VMEM. A single stacked array per K/V keeps jit donation
  trivial (the cache is donated every step, so updates alias in place).
- Host side: ``PageAllocator`` — a free-list allocator with optional
  copy-on-write-free refcounts, mirroring vLLM's block manager role. Page 0 is
  reserved as a scrap page: padding tokens write there so scatter updates need
  no masking inside jit.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig, CacheConfig
from ..utils import cdiv, get_logger

logger = get_logger("kv_cache")

# Page 0 never backs real tokens; padding slots scatter into it.
SCRAP_PAGE = 0


class KVCache(NamedTuple):
    """Device-side paged KV pool. k/v: [L, P, page_size, n_kv * head_dim]."""
    k: jax.Array
    v: jax.Array

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def allocate_kv_cache(
    model: ModelConfig,
    cache: CacheConfig,
    num_pages: int,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> KVCache:
    dtype = jnp.dtype(cache.dtype) if cache.dtype else model.jnp_dtype
    shape = (model.num_layers, num_pages, cache.page_size,
             model.num_kv_heads * model.head_dim)
    def mk():
        return jnp.zeros(shape, dtype=dtype)
    if sharding is not None:
        mk_sharded = jax.jit(mk, out_shardings=sharding)
        return KVCache(k=mk_sharded(), v=mk_sharded())
    return KVCache(k=mk(), v=mk())


def kv_cache_bytes_per_page(model: ModelConfig, cache: CacheConfig) -> int:
    dtype = jnp.dtype(cache.dtype) if cache.dtype else model.jnp_dtype
    per_tok = model.num_kv_heads * model.head_dim * dtype.itemsize
    return 2 * model.num_layers * cache.page_size * per_tok


def derive_num_pages(
    model: ModelConfig,
    cache: CacheConfig,
    max_model_len: int,
    max_num_seqs: int,
    hbm_free_bytes: Optional[int] = None,
) -> int:
    """Size the page pool. If ``cache.num_pages`` is set, use it; else use
    ``hbm_utilization`` of free HBM (the reference's gpuMemoryUtilization
    semantics); else fall back to enough pages for max_num_seqs full-length
    sequences (CPU/test path)."""
    if cache.num_pages is not None:
        return cache.num_pages
    if hbm_free_bytes is not None:
        budget = int(hbm_free_bytes * cache.hbm_utilization)
        n = budget // kv_cache_bytes_per_page(model, cache)
        if n < 2:
            raise ValueError(
                f"HBM budget {budget} too small for even 2 KV pages "
                f"({kv_cache_bytes_per_page(model, cache)} B/page)")
        return n
    pages_per_seq = cdiv(max_model_len, cache.page_size)
    return max_num_seqs * pages_per_seq + 1  # +1 scrap page


class PageAllocator:
    """Free-list page allocator with refcounts (enables future copy-on-write
    prefix sharing). All operations O(1) amortized. Host-side only — the device
    never sees this object, just the block tables it produces."""

    def __init__(self, num_pages: int, page_size: int):
        assert num_pages >= 2, "need at least scrap page + 1 usable page"
        self.num_pages = num_pages
        self.page_size = page_size
        # Page 0 is the scrap page and never allocatable.
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._refcount: dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int) -> list[int]:
        if not self.can_allocate(n):
            raise RuntimeError(f"KV page pool exhausted: want {n}, free {self.num_free}")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refcount[p] = 1
        return pages

    def fork(self, page: int) -> None:
        """Increment refcount (copy-on-write prefix sharing)."""
        self._refcount[page] += 1

    def free(self, pages: list[int]) -> None:
        for p in pages:
            rc = self._refcount.get(p)
            if rc is None:
                raise RuntimeError(f"double free of page {p}")
            if rc == 1:
                del self._refcount[p]
                self._free.append(p)
            else:
                self._refcount[p] = rc - 1

    def pages_for_tokens(self, num_tokens: int) -> int:
        return cdiv(num_tokens, self.page_size)


class PrefixCache:
    """Automatic prefix caching: full prompt pages are content-addressed by a
    CHAINED digest (page i's key commits to all tokens 0..(i+1)*ps), so a new
    request whose prompt shares a page-aligned prefix with any previously
    served one reuses those KV pages instead of recomputing them — the
    vLLM `enable_prefix_caching` capability, TPU-shaped: a cache hit turns
    admission into a chunked prefill whose "history" is the shared pages, so
    no new kernel is needed.

    Ownership: the cache holds ONE refcount on every cached page (pages are
    append-only, so content can never change while a reference exists).
    Sequences that reuse a page fork it (+1). Eviction is LRU and drops only
    the cache's own reference; pages still used by live sequences survive
    until their refcount drains. Digests are blake2b-chained — no
    Python-hash collisions serving wrong context.
    """

    def __init__(self, allocator: "PageAllocator"):
        self.allocator = allocator
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()  # digest->page
        # digest -> child digests: a chained child is only reachable through
        # its parent, so eviction must take descendants along or they would
        # sit unreachable while pinning page references.
        self._children: dict[bytes, set] = {}
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def _page_digests(token_ids: list[int], n_pages: int, ps: int):
        """Chained blake2b digest per full page, yielded lazily (a lookup
        that misses on page 0 must not hash a hundred-page prompt)."""
        raw = np.asarray(token_ids[:n_pages * ps], np.int32).tobytes()
        digest = b""
        for i in range(n_pages):
            h = hashlib.blake2b(digest, digest_size=16)
            h.update(raw[i * ps * 4:(i + 1) * ps * 4])
            digest = h.digest()
            yield digest

    def lookup(self, token_ids: list[int],
               max_tokens: Optional[int] = None) -> tuple[list[int], int]:
        """Longest page-aligned cached prefix of ``token_ids`` (capped at
        ``max_tokens``). Returns (forked page ids, matched token count) —
        caller owns one reference per returned page."""
        ps = self.allocator.page_size
        n = len(token_ids) // ps
        if max_tokens is not None:
            n = min(n, max_tokens // ps)
        pages: list[int] = []
        matched = 0
        for digest in self._page_digests(token_ids, n, ps):
            page = self._entries.get(digest)
            if page is None:
                break
            self._entries.move_to_end(digest)       # LRU touch
            pages.append(page)
            matched += ps
        for p in pages:
            self.allocator.fork(p)
        if matched:
            self.hits += 1
        else:
            self.misses += 1
        return pages, matched

    def register(self, token_ids: list[int], pages: list[int]) -> None:
        """Register the full pages backing ``token_ids`` (a completed prompt
        prefill). First registration of a digest wins; already-cached pages
        are left alone (dedupe)."""
        ps = self.allocator.page_size
        n = min(len(pages), len(token_ids) // ps)
        parent = b""
        for i, digest in enumerate(self._page_digests(token_ids, n, ps)):
            if digest not in self._entries:
                self.allocator.fork(pages[i])       # the cache's reference
                self._entries[digest] = pages[i]
                if parent:
                    self._children.setdefault(parent, set()).add(digest)
            parent = digest

    def evict(self, n_pages: int) -> int:
        """Drop LRU entries (each with its now-unreachable descendants)
        until ``n_pages`` entries were dropped or the cache is empty.
        Freeing only releases the cache's reference — shared pages stay
        alive for their sequences."""
        dropped = 0
        while dropped < n_pages and self._entries:
            digest, _ = next(iter(self._entries.items()))  # LRU head
            dropped += self._drop_subtree(digest)
        return dropped

    def _drop_subtree(self, digest: bytes) -> int:
        dropped = 0
        stack = [digest]
        while stack:
            d = stack.pop()
            page = self._entries.pop(d, None)
            if page is None:
                continue
            self.allocator.free([page])
            dropped += 1
            stack.extend(self._children.pop(d, ()))
        return dropped


class CachingPageAllocator(PageAllocator):
    """PageAllocator that transparently evicts prefix-cache entries under
    pressure, so every existing can_allocate/allocate call site (scheduler
    admission, decode window growth, chunk growth) gets eviction for free."""

    def __init__(self, num_pages: int, page_size: int):
        super().__init__(num_pages, page_size)
        self.prefix_cache = PrefixCache(self)

    def can_allocate(self, n: int) -> bool:
        # Evicting an entry only frees its page when no live sequence shares
        # it, so keep evicting until satisfied or the cache runs dry.
        while len(self._free) < n and len(self.prefix_cache):
            if self.prefix_cache.evict(n - len(self._free)) == 0:
                break
        return len(self._free) >= n
