from .kv_cache import KVCache, PageAllocator  # noqa: F401
from .sampling_params import SamplingParams  # noqa: F401
from .sequence import Sequence, SequenceStatus, FinishReason  # noqa: F401
from .scheduler import Scheduler, ScheduledBatch  # noqa: F401
from .engine import LLMEngine, RequestOutput  # noqa: F401
