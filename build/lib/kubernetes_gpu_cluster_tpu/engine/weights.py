"""Weight loading: local HF safetensors checkpoints -> stacked params pytree.

The reference pre-staged model weights on every node and mounted them via
hostPath (``old_README.md:1482-1561``, ``values-01-minimal-example3.yaml:22-30``)
— the same zero-egress deployment story applies here: weights are read from a
LOCAL directory (git-lfs clone / rsync, as the reference did), never
downloaded at serving time.

Mapping: HF per-layer tensors (torch ``[out, in]`` convention) are transposed
to our right-multiply ``[in, out]`` layout and STACKED along a leading [L]
axis to match models/llama.py's scanned-layer params. Families covered match
config/model_config.py: llama-class (Llama 1/2/3, TinyLlama), Qwen2/2.5
(attention bias), Qwen3 (qk-norm, tied embeddings), Mixtral (MoE experts).

Memory discipline: tensors are read lazily from the safetensors mmap and
written straight into preallocated per-parameter numpy buffers, so peak host
memory is ~one copy of the model (required for 8B on a 16G host; 70B loads
are expected to run sharded, one host per PP stage / TP shard via
``shardings``, where jax.device_put uploads only the addressable shards).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..config.model_config import MODEL_PRESETS
from ..utils import get_logger

logger = get_logger("engine.weights")

Params = dict[str, Any]


def config_from_hf(path: str, name: Optional[str] = None) -> ModelConfig:
    """Build a ModelConfig from a local HF checkpoint's config.json — any
    llama/qwen2/qwen3/mixtral-architecture model works without a preset."""
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    num_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // num_heads
    rope_scaling = None
    if hf.get("rope_scaling"):
        from ..ops.rope import scaled_inv_freq
        raw = {k: v for k, v in hf["rope_scaling"].items()
               if isinstance(v, (str, int, float, bool))}
        # Validate NOW — an unsupported type (yarn, dynamic, ...) must fail
        # the load, not silently serve with unscaled RoPE.
        scaled_inv_freq(head_dim, float(hf.get("rope_theta", 10000.0)), raw)
        rope_scaling = tuple(sorted(raw.items()))
    return ModelConfig(
        name=name or os.path.basename(os.path.normpath(path)),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=head_dim,
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        attention_bias=bool(hf.get("attention_bias",
                                   arch == "Qwen2ForCausalLM")),
        qk_norm=arch == "Qwen3ForCausalLM",
        num_experts=hf.get("num_local_experts", 0),
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        max_model_len=min(int(hf.get("max_position_embeddings", 4096)), 8192),
    )


class _Checkpoint:
    """All *.safetensors files of a checkpoint dir behind one name->tensor
    lookup (lazy: tensors are materialized per get())."""

    def __init__(self, path: str):
        from safetensors import safe_open

        self._handles = []
        self._index: dict[str, int] = {}
        files = sorted(f for f in os.listdir(path)
                       if f.endswith(".safetensors"))
        if not files:
            raise FileNotFoundError(f"no *.safetensors under {path}")
        for f in files:
            h = safe_open(os.path.join(path, f), framework="np")
            i = len(self._handles)
            self._handles.append(h)
            for key in h.keys():
                self._index[key] = i
        logger.info("checkpoint %s: %d files, %d tensors", path, len(files),
                    len(self._index))

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> np.ndarray:
        arr = self._handles[self._index[key]].get_tensor(key)
        if arr.dtype == np.dtype("V2"):   # raw bf16 comes back as void16
            arr = arr.view(jnp.bfloat16)
        return arr

    def get_t(self, key: str) -> np.ndarray:
        """Fetch a torch [out, in] matrix as [in, out]."""
        return np.ascontiguousarray(self.get(key).T)


def load_weights(path: str, cfg: ModelConfig,
                 shardings: Optional[Any] = None,
                 dtype: Optional[jnp.dtype] = None) -> Params:
    """Load a local HF checkpoint into the stacked-layer params pytree of
    models/llama.py. ``shardings`` is an optional matching pytree of
    NamedShardings (parallel.sharding.param_shardings) — with it, each
    parameter is placed sharded (jax.device_put with a sharding uploads only
    the addressable shards)."""
    ckpt = _Checkpoint(path)
    dtype = dtype or cfg.jnp_dtype
    L = cfg.num_layers

    def stack(keys_fn, transpose=True) -> np.ndarray:
        """Stack per-layer tensors into one [L, ...] array without holding
        more than one extra layer copy."""
        first = ckpt.get_t(keys_fn(0)) if transpose else ckpt.get(keys_fn(0))
        out = np.empty((L,) + first.shape, dtype=first.dtype)
        out[0] = first
        for l in range(1, L):
            out[l] = ckpt.get_t(keys_fn(l)) if transpose else ckpt.get(keys_fn(l))
        return out

    pre = "model.layers.{}."
    layers: Params = {
        "input_norm": stack(lambda l: pre.format(l) + "input_layernorm.weight",
                            transpose=False),
        "post_attn_norm": stack(
            lambda l: pre.format(l) + "post_attention_layernorm.weight",
            transpose=False),
        "wq": stack(lambda l: pre.format(l) + "self_attn.q_proj.weight"),
        "wk": stack(lambda l: pre.format(l) + "self_attn.k_proj.weight"),
        "wv": stack(lambda l: pre.format(l) + "self_attn.v_proj.weight"),
        "wo": stack(lambda l: pre.format(l) + "self_attn.o_proj.weight"),
    }
    if cfg.attention_bias:
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                             ("bv", "v_proj")):
            layers[ours] = stack(
                lambda l, t=theirs: pre.format(l) + f"self_attn.{t}.bias",
                transpose=False)
    if cfg.qk_norm:
        layers["q_norm"] = stack(
            lambda l: pre.format(l) + "self_attn.q_norm.weight", transpose=False)
        layers["k_norm"] = stack(
            lambda l: pre.format(l) + "self_attn.k_norm.weight", transpose=False)
    if cfg.is_moe:
        E = cfg.num_experts
        layers["router"] = stack(
            lambda l: pre.format(l) + "block_sparse_moe.gate.weight")

        def stack_experts(w_name: str) -> np.ndarray:
            first = ckpt.get_t(
                pre.format(0) + f"block_sparse_moe.experts.0.{w_name}.weight")
            out = np.empty((L, E) + first.shape, dtype=first.dtype)
            for l in range(L):
                for e in range(E):
                    out[l, e] = ckpt.get_t(
                        pre.format(l)
                        + f"block_sparse_moe.experts.{e}.{w_name}.weight")
            return out

        layers["w_gate"] = stack_experts("w1")
        layers["w_up"] = stack_experts("w3")
        layers["w_down"] = stack_experts("w2")
    else:
        layers["w_gate"] = stack(lambda l: pre.format(l) + "mlp.gate_proj.weight")
        layers["w_up"] = stack(lambda l: pre.format(l) + "mlp.up_proj.weight")
        layers["w_down"] = stack(lambda l: pre.format(l) + "mlp.down_proj.weight")

    params: Params = {
        "embed": ckpt.get("model.embed_tokens.weight"),
        "final_norm": ckpt.get("model.norm.weight"),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        if "lm_head.weight" in ckpt:
            params["lm_head"] = ckpt.get_t("lm_head.weight")
        else:   # checkpoint ties even though config doesn't say so
            params["lm_head"] = np.ascontiguousarray(params["embed"].T)

    if cfg.quantization:
        # Host-side (numpy) so the device never sees the full-precision
        # weights; the int8 tensors upload at half the bytes.
        from ..ops.quant import quantize_params
        params = quantize_params(params, cfg.quantization)

    def put(path_, x):
        name = path_[-1].key if hasattr(path_[-1], "key") else str(path_[-1])
        if x.dtype == np.int8 or name.endswith("_scale"):
            x = jnp.asarray(x)          # int8 weights / f32 scales as-is
        else:
            x = jnp.asarray(x, dtype=dtype)
        if shardings is not None:
            s = shardings
            for k in path_:
                s = s[k.key] if hasattr(k, "key") else s[k]
            return jax.device_put(x, s)
        return jax.device_put(x)

    out = jax.tree_util.tree_map_with_path(put, params)
    n_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(out))
    logger.info("loaded %s: %.2f GB as %s", cfg.name, n_bytes / 1e9, dtype)
    return out


def resolve_model(model_url: str, name: Optional[str] = None):
    """The reference's ``modelURL`` semantics (HF id OR local path,
    ``values-01-minimal-example3.yaml:8,22-30``): a local directory with
    config.json -> (config_from_hf, weights+tokenizer from it); otherwise a
    preset name -> (preset config, random init, byte tokenizer)."""
    if os.path.isdir(model_url) and os.path.exists(
            os.path.join(model_url, "config.json")):
        cfg = config_from_hf(model_url, name)
        return cfg, model_url, model_url
    from ..config import get_model_config
    return get_model_config(model_url), None, None
