from .llama import (  # noqa: F401
    PrefillMeta,
    DecodeMeta,
    init_params,
    forward_prefill,
    forward_decode,
    compute_logits,
)
from .registry import get_model_config  # noqa: F401
