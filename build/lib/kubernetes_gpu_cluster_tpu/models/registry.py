"""Model registry: the reference's ``modelURL`` semantics, natively.

A modelSpec's ``modelURL`` is either an HF-style id mapped to a preset, or a
local checkpoint directory pre-staged on the node (the reference staged
models to ``/models/<name>`` on every node and hostPath-mounted them,
``old_README.md:1482-1561``, ``values-01-minimal-example3.yaml:8,22-30``).
``resolve()`` turns that one string into everything the engine needs: an
architecture config, a weights source, and a tokenizer source. All families
share one decoder implementation (models/llama.py), specialized purely by
ModelConfig.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..config.model_config import MODEL_PRESETS, ModelConfig, get_model_config  # noqa: F401


@dataclasses.dataclass(frozen=True)
class ResolvedModel:
    config: ModelConfig
    weights_path: Optional[str]     # None -> random init (debug/bench)
    tokenizer_path: Optional[str]   # None -> byte tokenizer


def resolve(model_url: str, name: Optional[str] = None) -> ResolvedModel:
    """modelURL (HF id, preset name, or local checkpoint dir) -> ResolvedModel."""
    from ..engine.weights import resolve_model

    cfg, weights, tokenizer = resolve_model(model_url, name)
    return ResolvedModel(config=cfg, weights_path=weights,
                         tokenizer_path=tokenizer)


def load(resolved: ResolvedModel, shardings=None):
    """Materialize params for a resolved model: real weights when staged,
    None (engine random-init) otherwise."""
    if resolved.weights_path is not None:
        from ..engine.weights import load_weights

        return load_weights(resolved.weights_path, resolved.config,
                            shardings=shardings)
    return None


def list_models() -> list[str]:
    return sorted(MODEL_PRESETS)
