"""Deployment surface: reference values schema -> Kubernetes manifests.

The reference's user-facing artifact is `helm install vllm/vllm-stack -f
values.yaml` driven by ``servingEngineSpec.modelSpec[]`` (reference
``values-01-minimal-example*.yaml``, ``old_README.md:1079-1082``). This
package is the TPU-native equivalent: :mod:`render` ingests that exact
values schema and emits Deployment/StatefulSet/Service/router manifests that
run THIS framework's serving engine on TPU nodes (``google.com/tpu``
resources from cluster/device-plugin instead of ``nvidia.com/gpu``;
``jax.distributed`` coordinator instead of ``raySpec`` Ray clusters).
"""

from .render import render_values, render_values_file  # noqa: F401
