"""Pallas/Mosaic TPU kernels for the serving hot loop.

The north-star requirement (BASELINE.json): "PagedAttention and
ragged-prefill rewritten as Pallas/XLA custom-calls". These kernels replace
the reference's vLLM CUDA PagedAttention (the engine inside the images that
reference ``values-01-minimal-example*.yaml`` deploy):

- paged_decode.py — decode attention streaming only the valid KV pages
  HBM->VMEM with double-buffered DMA and online softmax (the XLA fallback
  gathers the full padded page table instead).
- flash_prefill.py — ragged (segment-causal) flash attention for prefill,
  O(T) memory (the XLA fallback materializes the O(T^2) score matrix).

Both are numerically validated against the XLA reference implementations in
tests/test_pallas.py (interpret mode on CPU; compiled on real TPU).
"""

from .paged_decode import pallas_paged_decode
from .flash_prefill import flash_ragged_prefill

__all__ = ["pallas_paged_decode", "flash_ragged_prefill"]
