from .rope import apply_rope, rope_cos_sin  # noqa: F401
from .attention import (  # noqa: F401
    write_kv_pages_all,
    paged_decode_attention,
    ragged_prefill_attention,
)
from .sampling import sample_tokens  # noqa: F401
