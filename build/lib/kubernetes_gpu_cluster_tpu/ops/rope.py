"""Rotary position embeddings (half-split convention, matching HF llama/qwen).

Computed on the fly from integer positions — no precomputed cos/sin table to
keep resident or re-slice, which keeps decode steps free of dynamic-slice ops
on a side table and lets XLA fuse the rotation into the q/k projections.

Scaling: Llama-3.1/3.2 checkpoints ship ``rope_scaling`` (type "llama3") —
piecewise frequency rescaling that stretches low-frequency components by
``factor`` with a smooth ramp between the high/low wavelength cutoffs.
"linear" (positions / factor everywhere) is also supported. Both are
compile-time transforms of ``inv_freq``; unsupported types are rejected at
config load (engine/weights.config_from_hf), never silently ignored.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


def scaled_inv_freq(head_dim: int, theta: float,
                    scaling: Optional[dict] = None) -> np.ndarray:
    """Per-pair inverse frequencies [head_dim//2], with HF ``rope_scaling``
    applied. Pure numpy on static config — folded into the program as a
    constant."""
    half = head_dim // 2
    inv_freq = theta ** -(np.arange(half, dtype=np.float32) / half)
    if not scaling:
        return inv_freq
    kind = scaling.get("rope_type") or scaling.get("type")
    factor = float(scaling.get("factor", 1.0))
    if kind == "linear":
        return inv_freq / factor
    if kind == "llama3":
        lo_f = float(scaling.get("low_freq_factor", 1.0))
        hi_f = float(scaling.get("high_freq_factor", 4.0))
        orig = float(scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2.0 * np.pi / inv_freq
        # Wavelengths shorter than orig/hi_f keep full resolution; longer than
        # orig/lo_f are stretched by `factor`; in between, interpolate.
        ramp = (orig / wavelen - lo_f) / (hi_f - lo_f)
        smooth = np.clip(ramp, 0.0, 1.0)
        scaled = inv_freq * (smooth + (1.0 - smooth) / factor)
        return scaled.astype(np.float32)
    raise ValueError(f"unsupported rope_scaling type {kind!r} "
                     "(supported: llama3, linear)")


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float,
                 dtype=jnp.float32, scaling: Optional[dict] = None):
    """positions: [...] int32 -> cos/sin of shape [..., head_dim//2]."""
    inv_freq = jnp.asarray(scaled_inv_freq(head_dim, theta, scaling))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [..., n_heads, head_dim]; cos/sin: [..., head_dim//2] (broadcast over
    the heads axis). Half-split rotation: (x1, x2) -> (x1*c - x2*s, x2*c + x1*s).
    """
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    c = cos[..., None, :]
    s = sin[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
