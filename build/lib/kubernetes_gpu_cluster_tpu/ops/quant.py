"""Int8 weight-only quantization (W8A16) for the serving hot path.

Decode on TPU is weight-streaming-bound: every substep reads all matmul
weights from HBM (~2.7 ms floor for a 2.2 GB bf16 model on v5e). Per-output-
channel symmetric int8 halves those bytes — the activation path stays bf16,
and because the scale is per OUTPUT channel it factors OUT of the dot:

    dot(x, dequant(w_q)) == dot(x, w_q) * scale[None, :]

so XLA reads int8 straight from HBM, converts inside the dot fusion, and
applies one [out]-vector multiply on the f32 result. No dequantized copy of
the weights ever exists in HBM.

This is the quantization story the reference's engine exposed via vLLM flags
(``--kv-cache-dtype``/quantized checkpoints hinted at reference
``values-01-minimal-example8.yaml:29``); here it is a first-class engine
config (``ModelConfig.quantization = "int8"``), applied to any checkpoint at
load time — no pre-quantized artifacts needed.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# Weight names eligible for int8 (the big streamed matmuls). Norms, biases,
# embeddings and the MoE router stay high-precision: tiny, quality-critical.
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_tensor(w, xp=None):
    """w: [..., in, out] -> (w_q int8 [..., in, out], scale f32 [..., out]).
    Works on numpy and jax arrays (pass the array module as ``xp``)."""
    if xp is None:
        xp = np if isinstance(w, np.ndarray) else _jnp()
    wf = w.astype(xp.float32)
    amax = xp.max(xp.abs(wf), axis=-2)
    scale = xp.maximum(amax / 127.0, 1e-8).astype(xp.float32)
    w_q = xp.clip(xp.round(wf / scale[..., None, :]), -127, 127).astype(xp.int8)
    return w_q, scale


def quantize_params(params: dict[str, Any], method: str) -> dict[str, Any]:
    """Quantize the big matmul weights of a models/llama params pytree
    in place (returns the same dict). ``method``: only "int8"."""
    if method != "int8":
        raise ValueError(f"unsupported quantization {method!r} (int8)")
    layers = params["layers"]
    for key in QUANT_LAYER_KEYS:
        if key in layers:
            layers[key], layers[key + "_scale"] = quantize_tensor(layers[key])
    if "lm_head" in params:
        params["lm_head"], params["lm_head_scale"] = quantize_tensor(
            params["lm_head"])
    return params


def _jnp():
    import jax.numpy as jnp
    return jnp
