"""Structured logging for the framework.

The reference repo's only observability was colored bash ``log/warn/error``
helpers (reference ``k8s_setup.sh:49-51``, ``gpu-crio-setup.sh:9-11``). Here we
provide structured, leveled logging shared by the engine, server, and cluster
tools, controllable via ``KGCT_LOG_LEVEL`` (mirroring the reference's debug
knobs like ``VLLM_LOGGING_LEVEL`` / ``NVIDIA_LOG_LEVEL``,
reference ``old_README.md:998-1002,1130``).
"""

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"
_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("KGCT_LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
    root = logging.getLogger("kgct")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    """Return a logger namespaced under the framework root ``kgct``."""
    _configure_root()
    return logging.getLogger(f"kgct.{name}")
