from .math import cdiv, round_up  # noqa: F401
from .logging import get_logger  # noqa: F401
