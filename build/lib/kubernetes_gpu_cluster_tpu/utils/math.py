"""Small math helpers used across the engine and kernels."""


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    """Round ``x`` up to the nearest multiple of ``multiple``."""
    return cdiv(x, multiple) * multiple


def next_power_of_2(x: int) -> int:
    """Smallest power of two >= x (>=1). Used for shape bucketing so the
    jit cache stays small under continuous batching (no recompilation storms)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()
