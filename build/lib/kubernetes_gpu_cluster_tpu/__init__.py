"""kubernetes_gpu_cluster_tpu — a TPU-native cluster + LLM-serving framework.

A brand-new framework with the capabilities of the reference
``alikhabazian/Kubernetes-gpu-cluster`` repo (a Kubernetes GPU cluster serving
LLMs with vLLM), re-designed TPU-first:

- The **serving engine** (continuous batching, paged KV cache, OpenAI API,
  TP/PP/EP over ICI/DCN) is built in, in JAX/XLA/Pallas — the reference
  delegated this to vLLM CUDA images (reference ``values-01-minimal-example*.yaml``).
- The **cluster layer** (reset-first bootstrap, container runtime, kubeadm
  init/join, HA control plane, accelerator enablement) targets TPU VM pods
  (reference ``k8s_setup.sh``, ``gpu-crio-setup.sh``, ``multi-cp.md``).
- The **deployment surface** keeps the reference's Helm
  ``servingEngineSpec.modelSpec[]`` schema so operators can switch 1:1.

Subpackages:
    config    — typed config system (engine config + Helm-values-parity schema)
    models    — model families (llama-class dense, mixtral-class MoE)
    ops       — Pallas TPU kernels + XLA fallbacks (paged attention, ragged prefill)
    engine    — paged KV cache, continuous-batching scheduler, LLMEngine
    parallel  — mesh/sharding, TP/PP/EP/DP over ICI & DCN, jax.distributed bootstrap
    serving   — OpenAI-compatible API server, router, tokenizer, metrics
    deploy    — values-schema renderer emitting the k8s deployment manifests
    utils     — logging, math helpers

The node-level ops layer lives in the repo-root ``cluster/`` directory:
``cluster/scripts/`` (reset-first bootstrap, runtime, proxy) and
``cluster/device-plugin/`` (the C++ kubelet device plugin + DaemonSet).
"""

__version__ = "0.3.0"
