"""The container image surface: Dockerfiles must be internally consistent and
produce exactly the tags the deploy surface references.

The sandbox has no docker daemon, so these are static checks (stage graph,
COPY source paths, tag agreement); `docker/build.sh` is the buildable proof
on a docker host. Closes round-3 VERDICT missing #2: the renderer/manifests
pointed at images nothing in the repo could produce (reference deployed real
pullable images, values-01-minimal-example.yaml:5-8)."""

import re
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCKER = REPO / "docker"


def _parse_dockerfile(path: Path):
    stages, copies, copy_froms = [], [], []
    for line in path.read_text().splitlines():
        line = line.strip()
        m = re.match(r"FROM\s+(\S+)(?:\s+AS\s+(\S+))?", line, re.I)
        if m:
            stages.append((m.group(1), m.group(2)))
            continue
        m = re.match(r"COPY\s+--from=(\S+)\s+(\S+)\s+\S+", line, re.I)
        if m:
            copy_froms.append((m.group(1), m.group(2)))
            continue
        m = re.match(r"COPY\s+(.+)\s+\S+$", line, re.I)
        if m:
            copies.extend(m.group(1).split())
    return stages, copies, copy_froms


class TestServingDockerfile:
    DF = DOCKER / "Dockerfile.serving"

    def test_exists_with_expected_stages(self):
        stages, _, _ = _parse_dockerfile(self.DF)
        assert [s[1] for s in stages] == ["wheels", "runtime"]

    def test_copy_sources_exist_in_build_context(self):
        _, copies, _ = _parse_dockerfile(self.DF)
        # Build context is the repo root (build.sh passes REPO_ROOT).
        for src in copies:
            assert (REPO / src).exists(), f"COPY source missing: {src}"

    def test_copy_from_references_defined_stage(self):
        stages, _, copy_froms = _parse_dockerfile(self.DF)
        names = {s[1] for s in stages}
        for stage, _ in copy_froms:
            assert stage in names

    def test_entrypoint_console_script_is_declared(self):
        # ENTRYPOINT kgct-api-server must be an installed console script.
        pyproject = (REPO / "pyproject.toml").read_text()
        assert 'kgct-api-server = "kubernetes_gpu_cluster_tpu.serving.api_server:main"' in pyproject
        assert "kgct-api-server" in self.DF.read_text()
        from kubernetes_gpu_cluster_tpu.serving.api_server import main  # noqa: F401


class TestDevicePluginDockerfile:
    DF = DOCKER / "Dockerfile.device-plugin"

    def test_exists_with_expected_stages(self):
        stages, _, _ = _parse_dockerfile(self.DF)
        assert [s[1] for s in stages] == ["build", "runtime"]

    def test_copy_sources_exist(self):
        _, copies, copy_froms = _parse_dockerfile(self.DF)
        for src in copies:
            assert (REPO / src).exists(), f"COPY source missing: {src}"
        # The binary copied out of the build stage matches the Makefile's
        # output path (relative to the build stage's WORKDIR /src).
        assert any(p == "/src/cluster/device-plugin/build/kgct-tpu-device-plugin"
                   for _, p in copy_froms)
        mk = (REPO / "cluster/device-plugin/Makefile").read_text()
        assert "$(BUILD)/kgct-tpu-device-plugin" in mk and "BUILD := build" in mk


class TestTagAgreement:
    def test_build_script_tags_match_renderer_and_manifest(self):
        build_sh = (DOCKER / "build.sh").read_text()
        assert 'REGISTRY="${REGISTRY:-ghcr.io/kgct}"' in build_sh
        assert 'TAG="${TAG:-v0.3.0}"' in build_sh

        from kubernetes_gpu_cluster_tpu.deploy.render import DEFAULT_IMAGE
        assert DEFAULT_IMAGE == "ghcr.io/kgct/tpu-serving:v0.3.0"
        assert "tpu-serving Dockerfile.serving" in build_sh

        ds = (REPO / "cluster/device-plugin/manifest/daemonset.yaml").read_text()
        assert "image: ghcr.io/kgct/tpu-device-plugin:v0.3.0" in ds
        assert "tpu-device-plugin Dockerfile.device-plugin" in build_sh

    def test_build_script_is_executable_bash(self):
        path = DOCKER / "build.sh"
        assert path.stat().st_mode & 0o111, "build.sh must be executable"
        subprocess.run(["bash", "-n", str(path)], check=True)


class TestCheckGate:
    """Images cannot ship lint-dirty code: docker/build.sh runs
    scripts/check.sh (kgct-lint empty baseline + tier-1) before any
    docker build, with an explicit logged escape hatch only."""

    CHECK = REPO / "scripts" / "check.sh"

    def test_build_script_invokes_check_before_building(self):
        build_sh = (DOCKER / "build.sh").read_text()
        assert 'scripts/check.sh' in build_sh
        assert "KGCT_SKIP_CHECKS" in build_sh
        # the gate must run before the first image build
        assert build_sh.index("check.sh") < build_sh.index(
            "tpu-serving Dockerfile.serving")

    def test_check_script_is_executable_bash(self):
        assert self.CHECK.stat().st_mode & 0o111
        subprocess.run(["bash", "-n", str(self.CHECK)], check=True)

    def test_check_script_stages_and_pipefail(self):
        sh = self.CHECK.read_text()
        assert "set -euo pipefail" in sh
        # stage 1: the lint gate, same runner as tests/test_lint_clean.py
        assert "kubernetes_gpu_cluster_tpu.analysis.cli" in sh
        # stage 2: tier-1, with the tee'd exit status preserved
        assert "pytest tests/" in sh and "-m 'not slow'" in sh
        assert "PIPESTATUS" in sh

    def test_check_script_lint_stage_runs_clean(self):
        subprocess.run(["bash", str(self.CHECK), "--lint-only"],
                       check=True, cwd=REPO, capture_output=True)
