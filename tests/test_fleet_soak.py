"""Fleet chaos soak (ROADMAP 7a): sustained traffic through the router
while chaos churns the membership.

@slow: three stub replicas behind the real Router take continuous
streaming traffic while ``replica_kill_midstream`` severs live upstream
sockets (every client stream must still end complete — the failover
splice, zero dropped streams), ``replica_down`` cycles replicas out and
back (ONLY the downed replica's ~K/N affinity keys remap, each to its
ring successor, and every key comes home on recovery), and the
traffic-failure seam drives full quarantine -> probe-recovery round-trips
on the router's peer scoreboard without a single client-visible failure.
Engine-free on purpose: the soak pins the CONTROL plane (routing, splice,
reputation) — the KV-byte plane has its own two-server scenario in
tests/test_wire_integrity.py.
"""

import asyncio
import json

import pytest

from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.errors import (
    REQUEST_ID_HEADER, RESUME_MODE_HEADER)


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


TOKENS = [11, 22, 33, 44, 55, 66]
FULL_TEXT = [f"t{i} " for i in range(len(TOKENS))]


async def _soak_replica(chunk_gap_s=0.02):
    """A survivable stub replica: streams one SSE frame per token (with
    the kgct_token_ids ledger) and continues relayed streams on
    /internal/resume. Returns (runner, url, served, resumes)."""
    from aiohttp import web as aioweb

    served, resumes = [], []

    async def health(request):
        return aioweb.json_response({"status": "ok"})

    async def metrics(request):
        return aioweb.Response(text="", content_type="text/plain")

    def frame(i):
        return (b"data: " + json.dumps(
            {"choices": [{"text": f"t{i} "}],
             "kgct_token_ids": [TOKENS[i]]}).encode() + b"\n\n")

    async def completions(request):
        served.append(await request.json())
        resp = aioweb.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        for i in range(len(TOKENS)):
            await resp.write(frame(i))
            # One TCP chunk per frame: the router's per-chunk chaos check
            # (replica_kill_midstream counts relayed chunks) stays
            # deterministic.
            await asyncio.sleep(chunk_gap_s)
        await resp.write(b"data: [DONE]\n\n")
        return resp

    async def resume(request):
        envelope = await request.json()
        resumes.append({"rid": request.headers.get(REQUEST_ID_HEADER),
                        "envelope": envelope})
        relayed = envelope["relayed_token_ids"]
        resp = aioweb.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            RESUME_MODE_HEADER: "import"})
        await resp.prepare(request)
        for i in range(len(relayed), len(TOKENS)):
            await resp.write(frame(i))
        await resp.write(b"data: [DONE]\n\n")
        return resp

    app = aioweb.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/internal/resume", resume)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, f"http://127.0.0.1:{runner.addresses[0][1]}", \
        served, resumes


def _texts(body: bytes):
    """(texts, done) of one client-received SSE byte stream."""
    texts, done = [], False
    for part in body.split(b"\n\n"):
        for line in part.split(b"\n"):
            if not line.startswith(b"data:"):
                continue
            payload = line[5:].strip()
            if payload == b"[DONE]":
                done = True
            elif payload:
                doc = json.loads(payload)
                assert "error" not in doc, doc
                texts.append(doc["choices"][0]["text"])
    return texts, done


@pytest.mark.slow
@pytest.mark.chaos
class TestFleetChaosSoak:
    def test_sustained_traffic_survives_membership_churn(self, monkeypatch,
                                                         tmp_path):
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))
        N = 3

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            stubs = [await _soak_replica() for _ in range(N)]
            runners = [s[0] for s in stubs]
            urls = [s[1] for s in stubs]
            served = {urls[i]: stubs[i][2] for i in range(N)}
            resumes = [s[3] for s in stubs]
            router = Router(urls, health_interval_s=9999,
                            fail_threshold=99,
                            routing_policy="prefix-affinity")
            client = TestClient(TestServer(router.build_app()))
            await client.start_server()
            streams = kills = 0
            try:
                async def stream(session):
                    nonlocal streams
                    streams += 1
                    r = await client.post(
                        "/v1/completions",
                        json={"prompt": f"soak {session}",
                              "session_id": session, "max_tokens": 6,
                              "stream": True})
                    assert r.status == 200
                    texts, done = _texts(await r.read())
                    # THE soak invariant: whatever chaos is armed, the
                    # client sees one complete stream — never truncated,
                    # never an error frame, ledger stripped.
                    assert done and texts == FULL_TEXT, (session, texts)

                # -- phase 1: mid-stream kills under sustained load ------
                for rnd in range(9):
                    if rnd % 3 == 0:
                        configure_faults(
                            "replica_kill_midstream:after=2,times=1")
                        kills += 1
                    for j in range(3):
                        await stream(f"p1-{rnd}-{j}")
                    configure_faults(None)
                assert router.failovers_total["import"] == kills
                assert router.failovers_total["failed"] == 0
                # Each kill produced exactly one resume splice carrying
                # the relayed prefix (2 chunks relayed before the sever).
                all_resumes = [r for rs in resumes for r in rs]
                assert len(all_resumes) == kills
                assert all(r["envelope"]["relayed_token_ids"] == TOKENS[:2]
                           for r in all_resumes)

                # -- phase 2: replica_down churn, remap contract ---------
                keys = [f"soak-key-{i}".encode() for i in range(30)]

                def owners():
                    return {k: router._pick(affinity_key=k).url
                            for k in keys}

                baseline = owners()
                by_owner: dict = {}
                for k, u in baseline.items():
                    by_owner.setdefault(u, []).append(k)
                for cycle in range(N):
                    down_url = urls[cycle]
                    before = {u: len(served[u]) for u in urls}
                    configure_faults(f"replica_down:value={cycle}")
                    for r in router.replicas:
                        await router._check(r, startup=True)
                    configure_faults(None)
                    assert not router.replicas[cycle].healthy
                    churned = owners()
                    moved = {k for k in keys if churned[k] != baseline[k]}
                    # ~K/N remap: exactly the downed replica's keys move,
                    # each to ITS ring successor — never a reshuffle.
                    assert moved == set(by_owner[down_url]), \
                        f"cycle {cycle}: non-owned keys remapped"
                    assert len(moved) <= 2 * len(keys) // N
                    for k in moved:
                        assert churned[k] == next(
                            u for u in router.ring.walk(k) if u != down_url)
                    # Traffic keeps flowing during the downtime; the dead
                    # replica serves none of it.
                    for j in range(3):
                        await stream(f"p2-{cycle}-{j}")
                    assert len(served[down_url]) == before[down_url]
                    # Recovery: probes restore it, every key comes home.
                    router.replicas[cycle].benched_until = 0.0
                    for r in router.replicas:
                        await router._check(r)
                    assert router.replicas[cycle].healthy
                    assert owners() == baseline, \
                        f"cycle {cycle}: owners did not return"

                # -- phase 3: quarantine -> probe recovery round-trips ---
                victim = router.replicas[0]
                for trip in (1, 2):
                    # Three traffic failures through the proxy's failure-
                    # accounting seam: timeout-weight decay crosses the
                    # threshold on the third — one quarantine ENTRY.
                    for _ in range(3):
                        router._count_failure(
                            victim, RuntimeError("soak: injected timeout"))
                    assert router.peer_scores.quarantined(victim.url)
                    assert (router.peer_scores.quarantines[victim.url]
                            == trip)
                    # Quarantined = out of the pick walk; a mid-window
                    # healthy probe must NOT restore it early...
                    await router._check(victim)
                    assert router.peer_scores.quarantined(victim.url)
                    picked = {router._pick(affinity_key=k).url
                              for k in keys}
                    assert victim.url not in picked
                    # ...and the fleet absorbs its traffic unharmed.
                    before = len(served[victim.url])
                    for j in range(3):
                        await stream(f"p3-{trip}-{j}")
                    assert len(served[victim.url]) == before
                    # The 503 Retry-After derivation sees the window.
                    assert router._retry_after_s() >= 1
                    # Window lapses -> the next healthy probe IS the
                    # recovery probe: score restored, back in the walk.
                    router.peer_scores._until[victim.url] = 0.0
                    await router._check(victim)
                    assert not router.peer_scores.quarantined(victim.url)
                    assert (router.peer_scores.score(victim.url)
                            >= router.peer_scores.threshold)
                    assert owners() == baseline
                # Round-trips are attributed: entry counter + flight dump.
                rm = await client.get("/metrics")
                text = await rm.text()
                assert (f'kgct_peer_quarantines_total{{peer="{victim.url}"}}'
                        f" 2") in text
                quarantine_dumps = [e for e in
                                    router.flight.export()["events"]
                                    if e.get("kind") == "peer_quarantine"]
                assert len(quarantine_dumps) == 2
                # Zero dropped streams over the WHOLE soak, and the soak
                # actually soaked (every stub replica served traffic).
                assert streams == 9 * 3 + N * 3 + 2 * 3
                assert all(len(served[u]) > 0 for u in urls)
            finally:
                configure_faults(None)
                await client.close()
                for runner in reversed(runners):
                    await runner.cleanup()

        asyncio.run(scenario())
