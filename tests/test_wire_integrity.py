"""Trustworthy KV wire plane: end-to-end frame integrity + peer quarantine.

Tier-1 keeps the CHEAP pins: engine-free codec pins (integrity off is
byte-identical to the pre-integrity encoders; on, every seam detects a
flipped byte / a pre-integrity peer), engine-free PeerScoreboard window
arithmetic with an injected clock, an engine-free router Retry-After pin,
and ONE two-server HTTP chaos scenario proving the acceptance contract:
with ``kv_wire_corrupt`` injected on a fleet pull / handoff pull /
migration push, the final client output is byte-identical to recompute
(greedy AND seeded), the corruption is attributed in metrics + the flight
recorder, and the offending peer is quarantined then recovers via probe.
The sustained fleet soak lives in tests/test_fleet_soak.py (@slow).
"""

import asyncio
import json
import struct

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.fleet_cache import (
    PEER_QUARANTINE_S, PEER_QUARANTINE_THRESHOLD, PEER_SCORE_START,
    PeerScoreboard)
from kubernetes_gpu_cluster_tpu.serving.handoff import (
    HANDOFF_MAGIC, PrefixStreamDecoder, ProtocolSkewError,
    WireCorruptionError, decode_handoff, decode_spill_frame, encode_handoff,
    encode_prefix_frames, encode_spill_frame, verify_import_state)


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


def _engine_config():
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=96, swap_space_gb=0.0),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                                  decode_buckets=(1, 2),
                                  prefill_buckets=(32, 64, 128),
                                  decode_window=4, mixed_batch_enabled=False,
                                  enable_prefix_caching=True))


def _state(n_pages=5, dtype="float32", **extra):
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, n_pages, 16, 64)).astype(dtype)
    st = {"model": "debug-tiny", "page_size": 16, "dtype": dtype,
          "matched_tokens": n_pages * 16,
          "prompt_token_ids": list(range(n_pages * 16)),
          "k": k, "v": k + 1}
    st.update(extra)
    return st


def _header_of(blob: bytes) -> dict:
    """Parse a handoff frame's JSON header without the codec (so the pins
    below see the raw wire fields, pop-free)."""
    m = len(HANDOFF_MAGIC)
    (hlen,) = struct.unpack(">I", bytes(blob[m:m + 4]))
    return json.loads(bytes(blob[m + 4:m + 4 + hlen]))


class TestIntegrityCodec:
    """Engine-free pins of the integrity extension (serving/handoff.py)."""

    def test_integrity_off_is_pre_extension_wire_dialect(self):
        """Off (the default) carries NO integrity fields — byte-level the
        pre-integrity frame, so mixed fleets interoperate mid-rollout."""
        st = _state()
        blob = bytes(encode_handoff(st))
        hdr = _header_of(blob)
        assert "page_crc" not in hdr and "frame_crc" not in hdr
        dec = decode_handoff(blob)
        assert "_integrity" not in dec
        verify_import_state(dec)  # no-op without the stash
        assert np.array_equal(dec["k"], st["k"])
        # And the prefix stream likewise.
        part0 = next(iter(encode_prefix_frames(_state())))
        phdr = json.loads(bytes(part0[12:]))
        assert "page_crc" not in phdr and "frame_crc" not in phdr

    def test_handoff_roundtrip_with_integrity(self):
        st = _state()
        blob = encode_handoff(st, integrity=True)
        hdr = _header_of(blob)
        assert len(hdr["page_crc"]["k"]) == 5 and "frame_crc" in hdr
        dec = decode_handoff(blob)
        assert np.array_equal(dec["k"], st["k"])
        assert np.array_equal(dec["v"], st["v"])
        # The decode leaves the stash for the import-seam re-check, which
        # pops it (the engine's import validation never sees it).
        assert "_integrity" in dec
        verify_import_state(dec)
        assert "_integrity" not in dec

    def test_require_integrity_rejects_pre_integrity_frame(self):
        blob = encode_handoff(_state())
        with pytest.raises(ProtocolSkewError, match="pre-integrity"):
            decode_handoff(blob, require_integrity=True)

    def test_flipped_payload_byte_detected_and_named(self):
        blob = bytearray(encode_handoff(_state(), integrity=True))
        blob[-1] ^= 0xFF  # last byte = v payload, final page
        with pytest.raises(WireCorruptionError,
                           match=r"v page 4 checksum mismatch"):
            decode_handoff(blob)

    def test_tampered_crc_list_fails_frame_digest(self):
        """The frame digest covers the checksum metadata itself: altering
        a page_crc entry (without recomputing the digest) is caught before
        any per-page compare could be fooled."""
        st = _state()
        blob = bytes(encode_handoff(st, integrity=True))
        hdr = _header_of(blob)
        hdr["page_crc"]["k"][0] ^= 1
        hb = json.dumps(hdr).encode()
        m = len(HANDOFF_MAGIC)
        (hlen,) = struct.unpack(">I", blob[m:m + 4])
        forged = (HANDOFF_MAGIC + struct.pack(">I", len(hb)) + hb
                  + blob[m + 4 + hlen:])
        with pytest.raises(WireCorruptionError,
                           match="frame digest mismatch"):
            decode_handoff(forged)

    def test_import_seam_recheck_catches_post_decode_rot(self):
        dec = decode_handoff(encode_handoff(_state(), integrity=True))
        dec["k"][0, 2, 0, 0] += 1.0  # bit-rot while parked host-side
        with pytest.raises(WireCorruptionError,
                           match="k page 2 checksum mismatch"):
            verify_import_state(dec)

    def test_prefix_stream_verifies_incrementally(self):
        """A flipped byte in chunk N raises when THAT chunk completes —
        the importer aborts mid-stream, before the tail even arrives."""
        st = _state()
        parts = [bytearray(p) for p in
                 encode_prefix_frames(st, chunk_pages=2, integrity=True)]
        assert len(parts) == 4  # header + 3 slabs (2+2+1 pages)
        parts[1][10] ^= 0xFF  # first slab -> pages 0-1
        dec = PrefixStreamDecoder()
        dec.feed(bytes(parts[0]))
        with pytest.raises(WireCorruptionError, match="page [01]"):
            dec.feed(bytes(parts[1]))

    def test_prefix_stream_clean_roundtrip_with_integrity(self):
        st = _state()
        blob = b"".join(bytes(p) for p in
                        encode_prefix_frames(st, chunk_pages=2,
                                             integrity=True))
        dec = PrefixStreamDecoder(require_integrity=True)
        got = []
        for i in range(0, len(blob), 1000):
            got.extend(dec.feed(blob[i:i + 1000]))
        assert dec.done
        k = np.concatenate([ck for ck, _ in got], axis=1)
        assert np.array_equal(k, st["k"])

    def test_prefix_stream_skew_raises_at_header(self):
        parts = list(encode_prefix_frames(_state(), chunk_pages=2))
        with pytest.raises(ProtocolSkewError, match="pre-integrity"):
            PrefixStreamDecoder(require_integrity=True).feed(
                bytes(parts[0]))

    def test_spill_frame_roundtrip_corrupt_and_skew(self):
        rng = np.random.default_rng(1)
        k = rng.standard_normal((2, 1, 16, 64)).astype("float32")
        frame = encode_spill_frame("ab" * 32, k, k + 1, "debug-tiny", 16,
                                   integrity=True)
        digest, header, gk, gv = decode_spill_frame(
            frame, require_integrity=True)
        assert digest == "ab" * 32 and np.array_equal(gk, k)
        bad = bytearray(frame)
        bad[-1] ^= 0xFF
        with pytest.raises(WireCorruptionError, match="checksum mismatch"):
            decode_spill_frame(bytes(bad))
        plain = encode_spill_frame("ab" * 32, k, k + 1, "debug-tiny", 16)
        with pytest.raises(ProtocolSkewError):
            decode_spill_frame(plain, require_integrity=True)

    def test_bfloat16_pages_checksum_cleanly(self):
        """The byte-view CRC fold must not trip over dtypes numpy alone
        cannot hash/compare (the real KV dtype on accelerators)."""
        import ml_dtypes
        st = _state(dtype="float32")
        st["k"] = st["k"].astype(ml_dtypes.bfloat16)
        st["v"] = st["v"].astype(ml_dtypes.bfloat16)
        st["dtype"] = "bfloat16"
        dec = decode_handoff(encode_handoff(st, integrity=True))
        verify_import_state(dec)
        assert np.array_equal(dec["k"], st["k"])


class TestPeerScoreboard:
    """Engine-free pins of the reputation/quarantine window arithmetic
    (clock injected — no sleeps, no wall-clock flake)."""

    def _board(self):
        t = [0.0]
        sb = PeerScoreboard(clock=lambda: t[0])
        return sb, t

    def test_corruption_quarantines_instantly(self):
        sb, _ = self._board()
        assert sb.score("p") == PEER_SCORE_START
        assert sb.record_corruption("p") is True  # the ENTRY transition
        assert sb.quarantined("p") and sb.quarantines == {"p": 1}
        assert sb.retry_after_s("p") == pytest.approx(PEER_QUARANTINE_S)

    def test_timeouts_take_three(self):
        sb, _ = self._board()
        assert not sb.record_timeout("p") and not sb.quarantined("p")
        assert not sb.record_timeout("p") and not sb.quarantined("p")
        assert sb.record_timeout("p") is True
        assert sb.quarantined("p")
        assert sb.score("p") < PEER_QUARANTINE_THRESHOLD

    def test_window_extension_does_not_recount(self):
        sb, t = self._board()
        assert sb.record_corruption("p")
        t[0] = 10.0
        # An in-flight exchange failing INSIDE the window extends it but
        # is not a second quarantine entry (the metric counts entries).
        assert sb.record_corruption("p") is False
        assert sb.quarantines == {"p": 1}
        assert sb.retry_after_s("p") == pytest.approx(PEER_QUARANTINE_S)

    def test_window_decays_and_probe_recovers(self):
        sb, t = self._board()
        sb.record_corruption("p")
        t[0] = PEER_QUARANTINE_S / 2
        assert sb.retry_after_s("p") == pytest.approx(PEER_QUARANTINE_S / 2)
        t[0] = PEER_QUARANTINE_S + 1
        # Window lapsed: the peer is a probe candidate again...
        assert not sb.quarantined("p") and sb.retry_after_s("p") == 0.0
        # ...and one successful probe recovers it past the threshold.
        sb.record_ok("p")
        assert sb.score("p") >= PEER_QUARANTINE_THRESHOLD
        assert not sb.quarantined("p")
        # A LATER corruption is a fresh entry (counter hits 2).
        assert sb.record_corruption("p") is True
        assert sb.quarantines == {"p": 2}

    def test_refailure_after_lapse_recounts(self):
        sb, t = self._board()
        sb.record_corruption("p")
        t[0] = PEER_QUARANTINE_S + 1
        # Probe FAILS (score still on the floor): fresh window, fresh entry.
        assert sb.record_corruption("p") is True
        assert sb.quarantines == {"p": 2} and sb.quarantined("p")

    def test_score_recovery_is_capped(self):
        sb, _ = self._board()
        sb.record_timeout("p")
        for _ in range(5):
            sb.record_ok("p")
        assert sb.score("p") == PEER_SCORE_START


class TestRouterQuarantine:
    """Engine-free: the router's scoreboard feeds _pick exclusion and the
    503 Retry-After derivation (the PR-2 admission-shed contract)."""

    def _router(self):
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        return Router(["http://a:1", "http://b:2"], health_interval_s=5.0)

    def test_pick_excludes_quarantined_until_desperation(self):
        r = self._router()
        r.peer_scores.record_corruption("http://a:1")
        for _ in range(4):
            assert r._pick().url == "http://b:2"
        # Desperation rounds (include_unhealthy) still see it: the router
        # degrades, it never refuses while a replica exists.
        urls = {r._pick(include_unhealthy=True).url for _ in range(8)}
        assert "http://a:1" in urls

    def test_retry_after_reflects_soonest_return(self):
        r = self._router()
        # One healthy replica: the soonest return is the next health tick.
        assert r._retry_after_s() == 5
        # Both quarantined: the soonest return is the shortest window.
        r.peer_scores.record_corruption("http://a:1")
        r.peer_scores.record_corruption("http://b:2")
        assert 1 <= r._retry_after_s() <= int(PEER_QUARANTINE_S) + 1
        assert r._retry_after_s() > 5

    def test_quarantine_counter_preseeded_in_metrics(self):
        r = self._router()
        text = asyncio.run(r.metrics(None)).text
        assert 'kgct_peer_quarantines_total{peer="http://a:1"} 0' in text
        r.peer_scores.record_corruption("http://a:1")
        assert ('kgct_peer_quarantines_total{peer="http://a:1"} 1'
                in asyncio.run(r.metrics(None)).text)


class TestWireChaosHTTP:
    """ONE two-server scenario over real sockets: the acceptance contract.
    kv_wire_corrupt on a fleet pull (greedy AND seeded), a handoff pull,
    and a migration push — every time the client output is byte-identical
    to recompute, the corruption is attributed (metrics + flight), the
    peer is quarantined and recovers via probe. Plus the receive-seam
    rejections: 426 protocol skew, 400 corrupt frame, 413 oversized
    bodies (spill + resume) before buffering."""

    def test_corrupt_quarantine_recover_and_receive_seams(self):
        from aiohttp import web as aioweb

        import aiohttp
        from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFILL_URL_HEADER, PREFIX_SOURCE_HEADER, REQUEST_ID_HEADER)

        async def scenario():
            runners = []

            async def serve(**kw):
                srv = build_server(_engine_config(), None, "debug-tiny",
                                   **kw)
                runner = aioweb.AppRunner(srv.build_app())
                await runner.setup()
                site = aioweb.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                runners.append(runner)
                return srv, f"http://127.0.0.1:{runner.addresses[0][1]}"

            def prompt(seed):
                return np.random.default_rng(seed).integers(
                    1, 200, 80).tolist()

            try:
                sa, ua = await serve(fleet_prefix_cache=True)
                sb, ub = await serve(fleet_prefix_cache=True,
                                     peer_pool=[ua], prefill_pool=[ua])
                assert sa.integrity_on and sb.integrity_on
                obs = sb.engine.engine.obs
                pulls = obs.fleet_pulls
                async with aiohttp.ClientSession() as sess:
                    async def comp(base, js, headers=None):
                        async with sess.post(f"{base}/v1/completions",
                                             json=js,
                                             headers=headers or {}) as resp:
                            assert resp.status == 200, await resp.text()
                            return (await resp.json())[
                                "choices"][0]["text"]

                    def probe_peer():
                        """Force the quarantine window to lapse (the
                        probe transition) without sleeping 30s."""
                        assert sb.peer_scores.quarantined(ua)
                        sb.peer_scores._until[ua] = 0.0
                        assert not sb.peer_scores.quarantined(ua)

                    # -- fleet pull corrupted in transit (greedy) --------
                    b1 = {"prompt": prompt(7), "max_tokens": 6,
                          "temperature": 0.0}
                    ref1 = await comp(ua, b1)
                    configure_faults("kv_wire_corrupt:times=1")
                    got1 = await comp(ub, b1,
                                      headers={PREFIX_SOURCE_HEADER: ua})
                    configure_faults(None)
                    assert got1 == ref1          # byte-identical recompute
                    assert pulls["recompute"] == 1 and pulls["ok"] == 0
                    # Attribution: counter, trace ring, flight recorder.
                    assert obs.wire_corruptions[("prefix", "corrupt")] == 1
                    flight = obs.flight.export()["events"]
                    assert any(e.get("kind") == "wire_corruption"
                               and e.get("path") == "prefix"
                               and e.get("peer") == ua for e in flight)
                    assert any(e.get("kind") == "peer_quarantine"
                               and e.get("peer") == ua for e in flight)
                    # The offender is quarantined: the next pull never
                    # touches the socket, recompute serves it.
                    assert sb.peer_scores.quarantined(ua)
                    b2 = {"prompt": prompt(8), "max_tokens": 6,
                          "temperature": 0.0}
                    ref2 = await comp(ua, b2)
                    got2 = await comp(ub, b2,
                                      headers={PREFIX_SOURCE_HEADER: ua})
                    assert got2 == ref2 and pulls["recompute"] == 2
                    assert any(e.args.get("reason") == "quarantined"
                               for e in obs.tracer.events()
                               if e.kind == "fleet_prefix")

                    # -- probe recovery: window lapses, one clean pull ---
                    probe_peer()
                    b3 = {"prompt": prompt(9), "max_tokens": 6,
                          "temperature": 0.0}
                    ref3 = await comp(ua, b3)
                    got3 = await comp(ub, b3,
                                      headers={PREFIX_SOURCE_HEADER: ua})
                    assert got3 == ref3 and pulls["ok"] == 1
                    assert (sb.peer_scores.score(ua)
                            >= PEER_QUARANTINE_THRESHOLD)
                    assert not sb.peer_scores.quarantined(ua)
                    assert sb.peer_scores.quarantines[ua] == 1

                    # -- fleet pull corrupted in transit (seeded) --------
                    b4 = {"prompt": prompt(10), "max_tokens": 6,
                          "temperature": 0.8, "seed": 11}
                    ref4 = await comp(ua, b4)
                    configure_faults("kv_wire_corrupt:times=1")
                    got4 = await comp(ub, b4,
                                      headers={PREFIX_SOURCE_HEADER: ua})
                    configure_faults(None)
                    assert got4 == ref4
                    assert obs.wire_corruptions[("prefix", "corrupt")] == 2
                    assert sb.peer_scores.quarantines[ua] == 2
                    probe_peer()
                    b5 = {"prompt": prompt(11), "max_tokens": 6,
                          "temperature": 0.0}
                    await comp(ua, b5)
                    await comp(ub, b5, headers={PREFIX_SOURCE_HEADER: ua})
                    assert pulls["ok"] == 2  # recovered again

                    # -- disaggregated handoff pull corrupted ------------
                    b6 = {"prompt": prompt(12), "max_tokens": 6,
                          "temperature": 0.0}
                    ref6 = await comp(ua, b6)
                    configure_faults("kv_wire_corrupt:times=1")
                    got6 = await comp(ub, b6,
                                      headers={PREFILL_URL_HEADER: ua})
                    configure_faults(None)
                    assert got6 == ref6          # local-prefill fallback
                    assert obs.wire_corruptions[("handoff", "corrupt")] == 1
                    assert sb.peer_scores.quarantines[ua] == 3
                    hand = [e for e in obs.tracer.events()
                            if e.kind == "handoff"
                            and e.args.get("side") == "integrity"]
                    assert any(e.args.get("path") == "handoff"
                               and e.args.get("peer") == ua for e in hand)

                    # -- stale-peer drill: exporter serves the
                    #    pre-integrity dialect, importer rejects loudly --
                    probe_peer()
                    sb.peer_scores.record_ok(ua)
                    b7 = {"prompt": prompt(13), "max_tokens": 6,
                          "temperature": 0.0}
                    ref7 = await comp(ua, b7)
                    configure_faults("peer_stale_frame:value=1,times=1")
                    got7 = await comp(ub, b7,
                                      headers={PREFIX_SOURCE_HEADER: ua})
                    configure_faults(None)
                    assert got7 == ref7
                    assert obs.wire_corruptions[("prefix", "skew")] == 1
                    # A skew detection carries corruption weight too: the
                    # stale peer is quarantined (4th entry).
                    assert sb.peer_scores.quarantines[ua] == 4

                    # -- migration push receive: corrupt -> 400, skew ->
                    #    426, both attributed on the RECEIVER ------------
                    mig = _state(mid_stream=True, output_token_ids=[1, 2])
                    blob = bytearray(encode_handoff(mig, integrity=True))
                    blob[-1] ^= 0xFF
                    hdr = {"Content-Type": "application/octet-stream",
                           REQUEST_ID_HEADER: "mig-corrupt-1"}
                    async with sess.post(f"{ub}/internal/kv_handoff",
                                         data=bytes(blob),
                                         headers=hdr) as resp:
                        assert resp.status == 400
                        assert "bad migration blob" in await resp.text()
                    assert obs.wire_corruptions[("migrate", "corrupt")] == 1
                    plain = bytes(encode_handoff(mig))  # pre-integrity
                    async with sess.post(f"{ub}/internal/kv_handoff",
                                         data=plain,
                                         headers=dict(
                                             hdr, **{REQUEST_ID_HEADER:
                                                     "mig-skew-1"})
                                         ) as resp:
                        assert resp.status == 426
                        assert "upgrade the peer" in await resp.text()
                    assert obs.wire_corruptions[("migrate", "skew")] == 1

                    # -- spill receive: skew 426, corrupt 400, oversized
                    #    413 BEFORE buffering ---------------------------
                    rng = np.random.default_rng(2)
                    pk = rng.standard_normal((2, 1, 16, 64)).astype(
                        "float32")
                    shdr = {"Content-Type": "application/octet-stream"}
                    plain_spill = encode_spill_frame(
                        "cd" * 32, pk, pk + 1, "debug-tiny", 16)
                    async with sess.post(f"{ub}/internal/fleet_spill",
                                         data=plain_spill,
                                         headers=shdr) as resp:
                        assert resp.status == 426
                    bad_spill = bytearray(encode_spill_frame(
                        "cd" * 32, pk, pk + 1, "debug-tiny", 16,
                        integrity=True))
                    bad_spill[-1] ^= 0xFF
                    async with sess.post(f"{ub}/internal/fleet_spill",
                                         data=bytes(bad_spill),
                                         headers=shdr) as resp:
                        assert resp.status == 400
                        assert "bad spill frame" in await resp.text()
                    assert obs.wire_corruptions[("spill", "skew")] == 1
                    assert obs.wire_corruptions[("spill", "corrupt")] == 1
                    async with sess.post(
                            f"{ub}/internal/fleet_spill",
                            data=b"\0" * (sb._spill_max_bytes + 1),
                            headers=shdr) as resp:
                        assert resp.status == 413
                    async with sess.post(
                            f"{ub}/internal/resume",
                            data=b"\0" * (sb._resume_max_bytes + 1),
                            headers={REQUEST_ID_HEADER: "resume-big-1"}
                            ) as resp:
                        assert resp.status == 413

                    # -- /metrics renders every series, seeded zeros
                    #    included ---------------------------------------
                    async with sess.get(f"{ub}/metrics") as resp:
                        text = await resp.text()
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="prefix",outcome="corrupt"} 2') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="handoff",outcome="corrupt"} 1') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="migrate",outcome="corrupt"} 1') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="migrate",outcome="skew"} 1') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="spill",outcome="corrupt"} 1') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="spill",outcome="skew"} 1') in text
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="resume",outcome="corrupt"} 0') in text
                    assert (f'kgct_peer_quarantines_total{{peer="{ua}"}} 4'
                            in text)
                    # The owner never saw a corruption: all zeros there.
                    async with sess.get(f"{ua}/metrics") as resp:
                        atext = await resp.text()
                    assert ('kgct_kv_wire_corruptions_total'
                            '{path="prefix",outcome="corrupt"} 0') in atext
            finally:
                for runner in reversed(runners):
                    await runner.cleanup()

        asyncio.run(scenario())


class TestIntegrityOffByteIdentical:
    """integrity_checks=False: the wire bytes are byte-identical to the
    pre-integrity encoders END TO END (server-level half of the rollout
    contract; the codec-level half is TestIntegrityCodec)."""

    def test_off_serves_pre_integrity_frames_and_interops(self):
        from aiohttp import web as aioweb

        import aiohttp
        from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFIX_SOURCE_HEADER)

        async def scenario():
            runners = []

            async def serve(**kw):
                srv = build_server(_engine_config(), None, "debug-tiny",
                                   **kw)
                runner = aioweb.AppRunner(srv.build_app())
                await runner.setup()
                site = aioweb.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                runners.append(runner)
                return srv, f"http://127.0.0.1:{runner.addresses[0][1]}"

            try:
                sa, ua = await serve(fleet_prefix_cache=True,
                                     integrity_checks=False)
                sb, ub = await serve(fleet_prefix_cache=True,
                                     peer_pool=[ua],
                                     integrity_checks=False)
                assert not sa.integrity_on and not sb.integrity_on
                prompt = np.random.default_rng(21).integers(
                    1, 200, 80).tolist()
                body = {"prompt": prompt, "max_tokens": 6,
                        "temperature": 0.0}
                async with aiohttp.ClientSession() as sess:
                    async def comp(base, js, headers=None):
                        async with sess.post(f"{base}/v1/completions",
                                             json=js,
                                             headers=headers or {}) as resp:
                            assert resp.status == 200, await resp.text()
                            return (await resp.json())[
                                "choices"][0]["text"]

                    ref = await comp(ua, body)
                    # An integrity-off pull works peer-to-peer (both sides
                    # speak the pre-integrity dialect)...
                    got = await comp(ub, body,
                                     headers={PREFIX_SOURCE_HEADER: ua})
                    assert got == ref
                    assert sb.engine.engine.obs.fleet_pulls["ok"] == 1
                    # ...and the exported stream carries NO integrity
                    # fields: byte-level the pre-integrity wire format.
                    async with sess.post(
                            f"{ua}/internal/fetch_prefix",
                            json={"prompt_token_ids": prompt,
                                  "have_tokens": 0}) as resp:
                        assert resp.status == 200
                        stream = await resp.read()
                    dec = PrefixStreamDecoder()
                    dec.feed(stream)
                    assert dec.header is not None
                    assert "page_crc" not in dec.header
                    assert "frame_crc" not in dec.header
            finally:
                for runner in reversed(runners):
                    await runner.cleanup()

        asyncio.run(scenario())
