"""Multi-host bootstrap test: 2 real OS processes over jax.distributed.

SURVEY §4's explicit gap: the reference could only test multi-node on real
machines (real IPs in multi-cp.md). Here two localhost CPU processes
bootstrap through the same K8s-style env contract the deploy renderer
injects into StatefulSet pods (KGCT_COORDINATOR / KGCT_NUM_PROCESSES /
KGCT_PROCESS_ID — parallel/mesh.initialize_distributed), build a global
2-device mesh, and run a psum + a sharded matmul across the process
boundary. This is the jax.distributed replacement for the reference's
Ray/KubeRay layer (old_README.md:1570-1625), tested without a cluster.
"""

from __future__ import annotations

import os
import pathlib
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh

initialize_distributed()   # reads KGCT_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()
assert jax.local_device_count() == 1

import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = make_mesh(dp=2)

# 1) cross-process psum: each rank contributes (rank+1); sum must be 3.
@jax.jit
def allreduce(x):
    return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                     in_specs=P("dp"), out_specs=P())(x)

rank = jax.process_index()
local = np.full((1, 4), rank + 1, np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, (2, 4))
out = allreduce(garr)
total = np.asarray(multihost_utils.process_allgather(out, tiled=True))
assert np.all(total == 3.0), total

# 2) dp-sharded matmul with a replicated weight (the engine's DP layout).
w = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
@jax.jit
def fwd(x, w):
    return x @ w
y = fwd(garr, w)
expect = np.full((1, 3), 0, np.float32)
y_local = np.asarray(y.addressable_shards[0].data)
ref = local @ np.arange(12, dtype=np.float32).reshape(4, 3)
assert np.allclose(y_local, ref), (y_local, ref)

print(f"RANK{rank}-OK")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="localhost gloo test")
def test_two_process_jax_distributed(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo = str(pathlib.Path(__file__).resolve().parent.parent)

    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)       # exactly one local CPU device each
        env.update({
            "KGCT_REPO": repo,
            "KGCT_COORDINATOR": f"127.0.0.1:{port}",
            "KGCT_NUM_PROCESSES": "2",
            "KGCT_PROCESS_ID": str(rank),
            "JAX_NUM_CPU_DEVICES": "1",
            "TPU_SKIP_MDS_QUERY": "1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}-OK" in out, (out, err[-1000:])


ENGINE_WORKER = r"""
import json, os, sys
import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh

initialize_distributed()
assert jax.process_count() == 2 and jax.device_count() == 2

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

# Both processes run the engine in SPMD lockstep: identical requests,
# identical host-side scheduling, one global tp=2 mesh spanning the two
# single-device processes — the StatefulSet serving layout (one engine pod
# per host, GSPMD over DCN).
cfg = EngineConfig(
    model=get_model_config("debug-tiny"),
    cache=CacheConfig(page_size=16, num_pages=64),
    scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                              decode_buckets=(1, 2, 4), prefill_buckets=(64, 128)))
mesh = make_mesh(tp=2)
eng = LLMEngine(cfg, mesh=mesh)
prompts = json.loads(os.environ["KGCT_TEST_PROMPTS"])
outs = eng.generate([list(p) for p in prompts],
                    SamplingParams(temperature=0.0, max_tokens=8))
toks = [o.output_token_ids for o in outs]
print(f"RANK{jax.process_index()}-TOKENS:" + json.dumps(toks))
"""


@pytest.mark.skipif(sys.platform != "linux", reason="localhost gloo test")
def test_two_process_full_engine(tmp_path):
    """The FULL LLMEngine across 2 OS processes (round-3 VERDICT missing #5):
    a tp=2 GSPMD mesh spanning two single-device jax.distributed processes
    must greedy-decode exactly the tokens the single-process engine produces
    — end-to-end proof of the StatefulSet/KGCT_* serving contract (the
    reference's cross-node serving, old_README.md:1615-1625)."""
    import json

    prompts = [[1, 5, 9, 2], [3, 3, 7]]

    # Single-process reference (same seed => identical random weights).
    from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                                   SchedulerConfig,
                                                   get_model_config)
    from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(64, 128)))
    ref = LLMEngine(cfg)
    expected = [o.output_token_ids for o in ref.generate(
        prompts, SamplingParams(temperature=0.0, max_tokens=8))]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "engine_worker.py"
    script.write_text(ENGINE_WORKER)
    repo = str(pathlib.Path(__file__).resolve().parent.parent)

    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update({
            "KGCT_REPO": repo,
            "KGCT_COORDINATOR": f"127.0.0.1:{port}",
            "KGCT_NUM_PROCESSES": "2",
            "KGCT_PROCESS_ID": str(rank),
            "JAX_NUM_CPU_DEVICES": "1",
            "TPU_SKIP_MDS_QUERY": "1",
            "KGCT_TEST_PROMPTS": json.dumps(prompts),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{err[-3000:]}"
        marker = f"RANK{rank}-TOKENS:"
        line = next(l for l in out.splitlines() if l.startswith(marker))
        got = json.loads(line[len(marker):])
        assert got == expected, (
            f"rank {rank} tokens diverged:\n{got}\nvs single-process:\n{expected}")


SERVING_LEADER = r"""
import asyncio, json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh

initialize_distributed()
from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import SamplingParams
from kubernetes_gpu_cluster_tpu.serving.async_engine import AsyncLLMEngine
from kubernetes_gpu_cluster_tpu.serving.multihost import (
    DirectiveLeader, follower_addrs_from_env)

cfg = EngineConfig(
    model=get_model_config("debug-tiny"),
    cache=CacheConfig(page_size=16, num_pages=64),
    scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                              decode_buckets=(1, 2, 4), prefill_buckets=(64, 128)))
eng = AsyncLLMEngine(cfg, mesh=make_mesh(tp=2),
                     leader=DirectiveLeader(follower_addrs_from_env()))

async def main():
    eng.start(asyncio.get_running_loop())
    prompts = json.loads(os.environ["KGCT_TEST_PROMPTS"])
    async def run_one(i, p):
        toks = []
        async for chunk in eng.generate(f"r{i}", list(p),
                                        SamplingParams(temperature=0.0,
                                                       max_tokens=8)):
            toks = chunk.output_token_ids
        return toks
    # Submit the second request mid-flight to exercise a non-trivial
    # directive stream (admissions at different step boundaries).
    t0 = asyncio.create_task(run_one(0, prompts[0]))
    await asyncio.sleep(0.2)
    t1 = asyncio.create_task(run_one(1, prompts[1]))
    out = [await t0, await t1]
    print("LEADER-TOKENS:" + json.dumps(out))

asyncio.run(main())
eng.shutdown()
"""

SERVING_FOLLOWER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.serving.multihost import DirectiveFollower

# Bind the directive listener BEFORE jax.distributed blocks on the group.
follower = DirectiveFollower(port=int(os.environ["KGCT_CONTROL_PORT"]))
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed()
from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine

cfg = EngineConfig(
    model=get_model_config("debug-tiny"),
    cache=CacheConfig(page_size=16, num_pages=64),
    scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                              decode_buckets=(1, 2, 4), prefill_buckets=(64, 128)))
eng = LLMEngine(cfg, mesh=make_mesh(tp=2))
follower.run(eng)
print("FOLLOWER-DONE")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="localhost gloo test")
def test_two_process_serving_leader_follower(tmp_path):
    """The PRODUCTION multihost serving topology: only rank 0 is driven (the
    AsyncLLMEngine front door, as behind the HTTP API), rank 1 follows the
    step-directive stream (serving/multihost.py) — and the pair must produce
    exactly the single-process engine's greedy tokens. This is what the
    rendered StatefulSet runs; the reference needed Ray for this role."""
    import json

    prompts = [[1, 5, 9, 2], [3, 3, 7]]

    from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                                   SchedulerConfig,
                                                   get_model_config)
    from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(64, 128)))
    expected = [o.output_token_ids for o in LLMEngine(cfg).generate(
        prompts, SamplingParams(temperature=0.0, max_tokens=8))]

    ports = []
    for _ in range(2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
    coord_port, ctrl_port = ports

    scripts = {0: tmp_path / "leader.py", 1: tmp_path / "follower.py"}
    scripts[0].write_text(SERVING_LEADER)
    scripts[1].write_text(SERVING_FOLLOWER)
    repo = str(pathlib.Path(__file__).resolve().parent.parent)

    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update({
            "KGCT_REPO": repo,
            "KGCT_COORDINATOR": f"127.0.0.1:{coord_port}",
            "KGCT_NUM_PROCESSES": "2",
            "KGCT_PROCESS_ID": str(rank),
            "KGCT_CONTROL_PORT": str(ctrl_port),
            "KGCT_FOLLOWER_ADDRS": f"127.0.0.1:{ctrl_port}",
            "JAX_NUM_CPU_DEVICES": "1",
            "TPU_SKIP_MDS_QUERY": "1",
            "KGCT_TEST_PROMPTS": json.dumps(prompts),
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(scripts[rank])], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    rc0, out0, err0 = outs[0]
    rc1, out1, err1 = outs[1]
    assert rc0 == 0, f"leader failed:\n{err0[-3000:]}"
    assert rc1 == 0, f"follower failed:\n{err1[-3000:]}"
    assert "FOLLOWER-DONE" in out1, (out1, err1[-800:])
    line = next(l for l in out0.splitlines() if l.startswith("LEADER-TOKENS:"))
    got = json.loads(line[len("LEADER-TOKENS:"):])
    assert got == expected, f"{got}\nvs single-process:\n{expected}"
