"""Multi-host bootstrap test: 2 real OS processes over jax.distributed.

SURVEY §4's explicit gap: the reference could only test multi-node on real
machines (real IPs in multi-cp.md). Here two localhost CPU processes
bootstrap through the same K8s-style env contract the deploy renderer
injects into StatefulSet pods (KGCT_COORDINATOR / KGCT_NUM_PROCESSES /
KGCT_PROCESS_ID — parallel/mesh.initialize_distributed), build a global
2-device mesh, and run a psum + a sharded matmul across the process
boundary. This is the jax.distributed replacement for the reference's
Ray/KubeRay layer (old_README.md:1570-1625), tested without a cluster.
"""

from __future__ import annotations

import os
import pathlib
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh

initialize_distributed()   # reads KGCT_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()
assert jax.local_device_count() == 1

import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = make_mesh(dp=2)

# 1) cross-process psum: each rank contributes (rank+1); sum must be 3.
@jax.jit
def allreduce(x):
    return shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                     in_specs=P("dp"), out_specs=P())(x)

rank = jax.process_index()
local = np.full((1, 4), rank + 1, np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local, (2, 4))
out = allreduce(garr)
total = np.asarray(multihost_utils.process_allgather(out, tiled=True))
assert np.all(total == 3.0), total

# 2) dp-sharded matmul with a replicated weight (the engine's DP layout).
w = jnp.arange(12, dtype=jnp.float32).reshape(4, 3)
@jax.jit
def fwd(x, w):
    return x @ w
y = fwd(garr, w)
expect = np.full((1, 3), 0, np.float32)
y_local = np.asarray(y.addressable_shards[0].data)
ref = local @ np.arange(12, dtype=np.float32).reshape(4, 3)
assert np.allclose(y_local, ref), (y_local, ref)

print(f"RANK{rank}-OK")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="localhost gloo test")
def test_two_process_jax_distributed(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo = str(pathlib.Path(__file__).resolve().parent.parent)

    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)       # exactly one local CPU device each
        env.update({
            "KGCT_REPO": repo,
            "KGCT_COORDINATOR": f"127.0.0.1:{port}",
            "KGCT_NUM_PROCESSES": "2",
            "KGCT_PROCESS_ID": str(rank),
            "JAX_NUM_CPU_DEVICES": "1",
            "TPU_SKIP_MDS_QUERY": "1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}-OK" in out, (out, err[-1000:])
