"""Observability subsystem unit tests: histogram exposition, trace ring +
Perfetto export, step-phase bookkeeping, lifecycle hooks, and the bench
output-assembly/emission contract (the driver parses stdout's LAST line)."""

import json
import logging
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_gpu_cluster_tpu.observability import (  # noqa: E402
    PHASES, Histogram, Observability, SLOTracker, render_gauge)
from kubernetes_gpu_cluster_tpu.observability.flightrecorder import (  # noqa: E402
    FlightRecorder)
from kubernetes_gpu_cluster_tpu.observability.phases import (  # noqa: E402
    StepPhaseStats)
from kubernetes_gpu_cluster_tpu.observability.trace import (  # noqa: E402
    RequestTracer, merge_perfetto)


class _Seq:
    """Minimal Sequence stand-in carrying the lifecycle fields the
    Observability hooks read/write."""

    def __init__(self, rid, arrival=100.0):
        self.request_id = rid
        self.arrival_time = arrival
        self.first_token_time = None
        self.scheduled_time = None
        self.finish_time = None
        self.preempt_count = 0
        self.num_prompt_tokens = 8
        self.num_output_tokens = 0


class TestHistogram:
    def test_empty_renders_zero_and_nan_free(self):
        h = Histogram("t_seconds", "help")
        lines = h.render()
        assert "# TYPE t_seconds histogram" in lines
        assert any(l == "t_seconds_count 0" for l in lines)
        assert any(l == "t_seconds_sum 0" for l in lines)
        assert not any("nan" in l.lower() for l in lines)

    def test_bucket_monotonicity_and_sum_count(self):
        h = Histogram("t_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = h.render()
        cums = [int(l.split()[-1]) for l in lines if "_bucket" in l]
        assert cums == sorted(cums)
        assert cums[-1] == 5                       # +Inf == count
        assert any(l == "t_seconds_count 5" for l in lines)
        [s] = [float(l.split()[-1]) for l in lines if l.startswith("t_seconds_sum")]
        assert abs(s - 56.05) < 1e-9

    def test_nan_observation_dropped(self):
        h = Histogram("t_seconds")
        h.observe(float("nan"))
        assert h.count == 0

    def test_labeled_cells_render_separately(self):
        h = Histogram("t_seconds", labels=("outcome",))
        h.observe(0.2, ("finished",))
        h.observe(3.0, ("aborted",))
        text = "\n".join(h.render())
        assert 'outcome="finished"' in text and 'outcome="aborted"' in text
        assert text.count("_count") == 2

    def test_render_gauge_absent_when_none(self):
        assert render_gauge("g", None) == []
        assert render_gauge("g", float("nan")) == []
        assert render_gauge("g", 0.5) == ["# TYPE g gauge", "g 0.5"]


class TestRequestTracer:
    def test_ring_bounded_and_disable(self):
        tr = RequestTracer(capacity=4)
        for i in range(10):
            tr.emit("queued", f"r{i}")
        evs = tr.events()
        assert len(evs) == 4 and evs[0].request_id == "r6"
        off = RequestTracer(enabled=False)
        off.emit("queued", "r0")
        assert off.events() == []

    def test_step_events_never_evict_request_events(self):
        # Sustained decode emits one engine-wide instant per step; a flood
        # of them must not push request-lifecycle events off the ring.
        tr = RequestTracer(capacity=8)
        tr.emit("arrival", "a")
        for _ in range(100):
            tr.emit("decode", "", batch=4, tokens=4)
        kinds = [e.kind for e in tr.events()]
        assert "arrival" in kinds
        assert kinds.count("decode") <= 2      # capacity // 4
        tr.clear()
        assert tr.events() == []

    def test_perfetto_spans_pair_and_orphan_close_synthesized(self):
        tr = RequestTracer()
        tr.emit("arrival", "a")
        tr.emit("first_token", "a", ttft_ms=5.0)
        tr.emit("finish", "a", outcome="finished")
        tr.emit("finish", "orphan", outcome="finished")  # arrival fell off
        doc = tr.export_perfetto()
        evs = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
        a_phs = [e["ph"] for e in evs if e.get("id") == "a"]
        assert a_phs == ["b", "n", "e"]
        orphan = [e for e in evs if e.get("id") == "orphan"]
        assert [e["ph"] for e in orphan] == ["b", "e"]   # synthesized open
        json.loads(json.dumps(doc))                      # wire-serializable

    def test_perfetto_step_slices(self):
        tr = RequestTracer()
        recs = [{"step": 1, "kind": "decode", "batch": 4,
                 "phases": [("device_dispatch", 10.0, 0.002),
                            ("device_fetch", 10.002, 0.001)]}]
        doc = tr.export_perfetto(step_records=recs)
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {s["name"] for s in slices} == {"device_dispatch",
                                               "device_fetch"}
        assert all(s["dur"] > 0 for s in slices)


class TestFlightRecorder:
    def test_ring_bounded_and_disable(self):
        fr = FlightRecorder(capacity=4, enabled=True)
        for i in range(10):
            fr.record("queued", f"r{i}", {"n": i})
        events = fr.export()["events"]
        assert len(events) == 4 and events[0]["request_id"] == "r6"
        off = FlightRecorder(enabled=False)
        off.record("queued", "r0")
        assert off.export()["events"] == []
        assert off.dump("anything") is None

    def test_tracer_mirror_is_independent_of_trace_toggle(self):
        # The flight recorder is the ALWAYS-ON crash capture: KGCT_TRACE=0
        # (tracer disabled) must not silence it — only KGCT_FLIGHT=0 does.
        fr = FlightRecorder(enabled=True)
        tr = RequestTracer(enabled=False, recorder=fr)
        tr.emit("arrival", "r1", prompt_tokens=8)
        assert tr.events() == []                      # trace ring: off
        [ev] = fr.export()["events"]
        assert ev["kind"] == "arrival" and ev["request_id"] == "r1"
        assert ev["prompt_tokens"] == 8

    def test_snapshot_source_and_interval(self):
        fr = FlightRecorder(enabled=True, snapshot_interval_s=0.0)
        calls = []
        fr.set_snapshot_source(lambda: calls.append(1) or {"waiting": 3})
        fr.maybe_snapshot()
        fr.maybe_snapshot()
        snaps = [e for e in fr.export()["events"] if e["kind"] == "snapshot"]
        assert len(snaps) == 2 and snaps[0]["waiting"] == 3
        # A long interval rate-limits: the second call within the window
        # is a single monotonic read, no snapshot.
        slow = FlightRecorder(enabled=True, snapshot_interval_s=3600)
        slow.set_snapshot_source(lambda: {"waiting": 0})
        slow.maybe_snapshot()
        slow.maybe_snapshot()
        assert len([e for e in slow.export()["events"]
                    if e["kind"] == "snapshot"]) == 1
        # A raising source never propagates (the step loop must survive).
        fr.set_snapshot_source(lambda: 1 / 0)
        fr.maybe_snapshot()

    def test_dump_writes_trigger_and_ring(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))
        fr = FlightRecorder(enabled=True)
        fr.record("arrival", "r1", {"prompt_tokens": 4})
        path = fr.dump("watchdog_trip", trips=2)
        assert path is not None and path.startswith(str(tmp_path))
        doc = json.loads(open(path).read())
        assert doc["reason"] == "watchdog_trip"
        assert doc["info"] == {"trips": 2}
        kinds = [e["kind"] for e in doc["events"]]
        assert kinds == ["arrival", "watchdog_trip"]   # trigger appended
        assert doc["events"][-1]["trips"] == 2
        assert fr.dumps_total == 1 and fr.last_dump_path == path
        # unix anchor converts monotonic event ts to wall clock
        assert doc["unix_minus_monotonic"] + doc["events"][0]["ts"] > 0


class TestSLOTracker:
    def test_attainment_and_default_budget(self):
        slo = SLOTracker()                 # no operator budget
        assert slo.budget_ms == 1000.0     # north-star bar
        assert slo.attainment() == 1.0     # empty window: nothing missed
        slo.on_first_token(0.5)
        slo.on_first_token(0.9)
        slo.on_first_token(2.0)            # blows the 1 s bar
        assert abs(slo.attainment() - 2 / 3) < 1e-9
        slo.ttft_budget_ms = 3000.0        # operator budget overrides
        assert slo.attainment() == 1.0

    def test_goodput_counts_only_budget_meeting_requests(self):
        import time as _time

        slo = SLOTracker(ttft_budget_ms=1000.0, goodput_window_s=10.0)
        assert slo.goodput_tokens_per_sec() == 0.0
        slo.on_finish(0.5, 40)             # met budget: counts
        slo.on_finish(5.0, 1000)           # blew budget: excluded
        slo.on_finish(0.2, 0)              # no tokens: excluded
        # Simulate a 10 s observed span: the denominator is the observed
        # elapsed time capped at the window, never the bare window (a
        # fresh server's goodput must not be systematically understated).
        slo._window_start = _time.monotonic() - 10.0
        assert abs(slo.goodput_tokens_per_sec() - 4.0) < 0.01
        # Short observed span: same tokens over ~2 s reads ~20 tok/s.
        slo._window_start = _time.monotonic() - 2.0
        assert abs(slo.goodput_tokens_per_sec() - 20.0) < 0.2
        slo.clear()
        assert slo.goodput_tokens_per_sec() == 0.0
        assert slo.attainment() == 1.0

    def test_window_is_bounded(self):
        slo = SLOTracker(ttft_budget_ms=1000.0, window=4)
        for _ in range(10):
            slo.on_first_token(9.0)        # all misses
        slo.on_first_token(0.1)            # one recent hit
        assert abs(slo.attainment() - 1 / 4) < 1e-9


class TestMergePerfetto:
    def _doc(self, rid, t0_unix):
        tr = RequestTracer()
        tr.emit("arrival", rid)
        tr.emit("finish", rid, outcome="finished")
        doc = tr.export_perfetto(process_name="p")
        doc["kgctT0Unix"] = t0_unix        # pin the anchor for determinism
        return doc

    def test_rebase_pid_and_labels(self):
        a = self._doc("req-1", 100.0)      # earliest process: origin
        b = self._doc("req-1", 100.5)      # starts 0.5 s later
        merged = merge_perfetto([("kgct-router", a), ("kgct-engine x", b)])
        assert merged["kgctT0Unix"] == 100.0
        names = {e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names == {"kgct-router", "kgct-engine x"}
        # Both processes carry the request span, correlated on the id...
        spans = [e for e in merged["traceEvents"]
                 if e.get("cat") == "request" and e.get("id") == "req-1"]
        assert {e["pid"] for e in spans} == {1, 2}
        # ...and the later process's events shifted by its anchor delta.
        b_open = min(e["ts"] for e in spans if e["pid"] == 2)
        assert b_open >= 0.5e6 - 1
        json.dumps(merged)                 # wire-serializable

    def test_empty_doc_merges_without_anchor(self):
        empty = RequestTracer().export_perfetto()
        assert empty["kgctT0Unix"] is None
        merged = merge_perfetto([("a", empty), ("b", self._doc("r", 5.0))])
        assert merged["kgctT0Unix"] == 5.0
        assert {e["pid"] for e in merged["traceEvents"]} == {1, 2}


class TestStepPhaseStats:
    def test_phase_context_accumulates(self):
        st = StepPhaseStats()
        st.start_step()
        with st.phase("schedule"):
            pass
        with st.phase("device_fetch"):
            pass
        st.end_step(step=1, kind="decode", batch=2, duration_s=0.01)
        assert st.counts["schedule"] == 1
        assert st.steps_recorded == 1
        assert st.step_records()[0]["kind"] == "decode"
        b = st.breakdown()
        assert set(b) == set(PHASES)
        assert b["schedule"]["count"] == 1

    def test_discard_drops_record_keeps_totals(self):
        st = StepPhaseStats()
        st.start_step()
        with st.phase("schedule"):
            pass
        total = st.totals["schedule"]
        st.discard_step()
        assert st.step_records() == []
        assert st.totals["schedule"] == total >= 0.0

    def test_detokenize_out_of_step_record(self):
        st = StepPhaseStats()
        st.record("detokenize", 0.004)
        assert st.counts["detokenize"] == 1
        assert st.breakdown()["detokenize"]["mean_ms"] == 4.0
        # Out-of-step slices must not touch the engine thread's step-local
        # state (they arrive from the HTTP event-loop thread mid-step) —
        # they surface through detached_records() instead.
        assert st._current == [] and st.current_durs == {}
        [rec] = st.detached_records()
        assert rec["kind"] == "http"
        assert [p[0] for p in rec["phases"]] == ["detokenize"]

    def test_clear_records_drops_rings_keeps_totals(self):
        st = StepPhaseStats()
        st.start_step()
        with st.phase("schedule"):
            pass
        st.end_step(step=1, kind="decode", batch=1, duration_s=0.01)
        st.record("detokenize", 0.002)
        st.clear_records()
        assert st.step_records() == [] and st.detached_records() == []
        assert st.counts["schedule"] == 1 and st.counts["detokenize"] == 1


class TestObservabilityLifecycle:
    def _run_request(self, obs, rid="r1", preempt=False):
        seq = _Seq(rid)
        obs.on_arrival(seq)
        obs.on_queued(seq, depth=1)
        seq.arrival_time = 0.0
        if preempt:
            obs.on_preempt(seq)
        obs.on_scheduled(seq, 1)
        seq.first_token_time = seq.scheduled_time + 0.05
        obs.on_first_token(seq, fetch_s=0.01)
        seq.num_output_tokens = 5
        obs.on_finish(seq, None)
        return seq

    def test_queue_ttft_e2e_histograms_fill(self):
        obs = Observability(enabled=True)
        self._run_request(obs)
        assert obs.queue_wait.count == 1
        assert obs.ttft.count == 1
        assert obs.e2e_latency.count == 1
        assert obs.tpot.count == 1
        d = obs.ttft_decomposition()
        assert d["samples"] == 1
        assert d["prefill_ms"] >= 0 and d["first_fetch_ms"] == 10.0

    def test_finish_idempotent_and_outcome_labels(self):
        obs = Observability(enabled=True)
        seq = self._run_request(obs, preempt=True)
        obs.on_finish(seq, None)       # double-finish: second is a no-op
        assert obs.e2e_latency.count == 1
        text = "\n".join(obs.e2e_latency.render())
        assert 'outcome="preempted"' in text

    def test_sampled_decode_ratio_gauge(self):
        obs = Observability(enabled=True)
        assert obs.sampled_decode_ratio() is None     # one mode only
        obs.on_step(1, "decode", 4, 0.1, 100, mode="greedy")
        assert obs.sampled_decode_ratio() is None
        obs.on_step(2, "decode", 4, 0.1, 90, mode="sampled")
        assert abs(obs.sampled_decode_ratio() - 0.9) < 1e-9
        text = "\n".join(obs.render_prometheus())
        assert "kgct_sampled_decode_ratio 0.9" in text

    def test_clear_trace_scopes_capture(self):
        obs = Observability(enabled=True)
        self._run_request(obs)
        obs.phases.start_step()
        with obs.phases.phase("device_dispatch"):
            pass
        obs.on_step(1, "decode", 1, 0.01, 1, mode="greedy")
        obs.phases.record("detokenize", 0.001)     # detached (HTTP thread)
        evs = obs.export_perfetto()["traceEvents"]
        assert {"device_dispatch", "detokenize"} <= {
            e["name"] for e in evs if e.get("ph") == "X"}
        obs.clear_trace()
        evs = obs.export_perfetto()["traceEvents"]
        # Metadata only: request spans, step slices AND detached slices all
        # emptied — a ?clear=1 scoped capture starts from nothing.
        assert {e.get("ph") for e in evs} == {"M"}
        assert obs.ttft.count == 1                 # /metrics state untouched

    def test_render_prometheus_fresh_is_nan_free(self):
        obs = Observability(enabled=True)
        text = "\n".join(obs.render_prometheus())
        assert "nan" not in text.lower()
        assert "kgct_step_phase_seconds_total" in text

    def test_aborted_requests_excluded_from_goodput(self):
        """Goodput counts DELIVERED work: an aborted request's tokens were
        generated but never received, so they must not inflate the
        autoscaler signal — a finished request with the same TTFT does."""
        obs = Observability(enabled=True)

        def run(rid, reason):
            seq = _Seq(rid)
            obs.on_arrival(seq)
            obs.on_scheduled(seq, 1)
            seq.arrival_time = seq.scheduled_time        # TTFT ~10 ms
            seq.first_token_time = seq.scheduled_time + 0.01
            obs.on_first_token(seq)
            seq.num_output_tokens = 50
            obs.on_finish(seq, reason)
        run("ra", "abort")
        assert obs.slo.goodput_tokens_per_sec() == 0.0
        run("rb", None)
        assert obs.slo.goodput_tokens_per_sec() > 0.0


class TestJsonLogFormat:
    def test_json_formatter_carries_request_id(self):
        from kubernetes_gpu_cluster_tpu.utils.logging import _JsonFormatter
        rec = logging.LogRecord("kgct.engine", logging.WARNING, __file__, 1,
                                "preempted %s", ("req-9",), None)
        rec.request_id = "req-9"
        entry = json.loads(_JsonFormatter().format(rec))
        assert entry["level"] == "WARNING"
        assert entry["msg"] == "preempted req-9"
        assert entry["request_id"] == "req-9"

    def test_plain_record_has_no_request_id(self):
        from kubernetes_gpu_cluster_tpu.utils.logging import _JsonFormatter
        rec = logging.LogRecord("kgct.x", logging.INFO, __file__, 1,
                                "hello", (), None)
        entry = json.loads(_JsonFormatter().format(rec))
        assert "request_id" not in entry


class TestBenchOutputContract:
    def _fake_results(self):
        return [{
            "model": "debug-tiny", "quantization": None, "batch": 8,
            "decode_window": 4, "prefill_budget": 256,
            "decode_tokens_per_sec": 123.4,
            "sampled_over_greedy": 0.95,
            "ttft_decomposition": {"queue_ms": 1.0, "prefill_ms": 2.0,
                                   "first_fetch_ms": 3.0, "samples": 8},
        }]

    def test_assemble_output_round_trips_json(self):
        import bench
        out = bench.assemble_output(self._fake_results(), "cpu")
        reparsed = json.loads(json.dumps(out))
        assert reparsed["value"] == 123.4
        assert reparsed["backend"] == "cpu"
        d = reparsed["ttft_decomposition"]
        assert {"queue_ms", "prefill_ms", "first_fetch_ms"} <= set(d)
        assert reparsed["sampled_over_greedy"] == 0.95
        assert not math.isnan(reparsed["vs_baseline"])

    def test_emit_result_last_stdout_line_parses(self, capsys):
        import bench
        print("some earlier unflushed noise")
        bench.emit_result(bench.assemble_output(self._fake_results(), "cpu"))
        captured = capsys.readouterr().out
        last = captured.rstrip("\n").splitlines()[-1]
        parsed = json.loads(last)
        assert parsed["unit"] == "tokens/s/chip"
