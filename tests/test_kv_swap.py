"""Two-tier KV cache: host-DRAM offload, preempt-by-swap, prefix-spill.

The contract under test (ISSUE 7 acceptance):

- a sequence preempted-by-swap and restored emits EXACTLY the same
  continuation as the same seed never preempted, and as the same seed
  recompute-preempted (greedy + seeded-sampled);
- ``swap_space_gb=0`` (the default) builds no swapper and keeps today's
  recompute-preemption behavior byte-identically;
- prefix-cache eviction spills to host and ``lookup`` restores from the
  host tier instead of re-prefilling;
- scheduler-level lifecycle: swap parks state (``num_prefilled`` survives),
  a failed swap-out degrades to recompute, aborts free host pages;
- the KGCT_SANITIZE KV-slot shadow accepts swapped-in slots as committed
  history (no false positives under swap churn).

Budget: one module-scoped engine trio covers the byte-identity pins AND the
metrics/trace assertions; the soak variant is @slow.
"""

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.scheduler import Scheduler
from kubernetes_gpu_cluster_tpu.engine.sequence import (Sequence,
                                                        SequenceStatus)

# The pressure shape of test_engine.py's preemption pins: 3 sequences whose
# decode growth exceeds a 7-usable-page pool, forcing preemption churn.
_PROMPTS = [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]]
_PARAMS = [
    SamplingParams(max_tokens=16, temperature=0.8, seed=11,
                   frequency_penalty=1.5, presence_penalty=0.5),
    SamplingParams(max_tokens=16, temperature=0.8, seed=22,
                   frequency_penalty=1.5),
    SamplingParams(max_tokens=16, temperature=0.0),
]


def _mk(num_pages, swap_gb=0.0, max_seqs=8, prefix=False, max_prefill=256):
    # decode_window=4 (not the default 8): halves the scan the decode
    # programs compile — byte-identity is window-invariant (pinned by
    # test_engine.TestDecodeWindowEquivalence) and tier-1 budget is tight.
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=num_pages,
                          swap_space_gb=swap_gb),
        scheduler=SchedulerConfig(
            max_num_seqs=max_seqs, max_prefill_tokens=max_prefill,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(32, 64, 128, 256),
            decode_window=4, enable_prefix_caching=prefix))
    return LLMEngine(cfg)


@pytest.fixture(scope="module")
def trio_outputs():
    """(reference outputs, swap engine, its outputs) — the swap engine comes
    back with its post-churn state intact for the metrics/trace pins."""
    big = _mk(num_pages=128)
    ref = big.generate(_PROMPTS, _PARAMS)
    del big
    swp = _mk(num_pages=8, swap_gb=0.05)
    swp_outs = swp.generate(_PROMPTS, _PARAMS)
    return ref, swp, swp_outs


def test_swap_restore_byte_identity(trio_outputs):
    """Greedy AND seeded-sampled (with penalties) continuations across a
    swap-preempt/restore cycle match the never-preempted run exactly — the
    restored pages are bit-copies of the committed KV. (The recompute arm of
    the same shape is pinned against the same reference by
    test_engine.py::test_preempted_seeded_penalized_output_unchanged, so
    swap == recompute follows transitively.)"""
    ref, swp, swp_outs = trio_outputs
    assert swp.scheduler.num_preemptions_by_kind["swap"] > 0
    assert swp.scheduler.num_preemptions_by_kind["recompute"] == 0
    for a, b in zip(ref, swp_outs):
        assert a.output_token_ids == b.output_token_ids


def test_swap_accounting_drains_and_swap_off_builds_nothing(trio_outputs):
    """After the churn drains: every device page is back in the free list
    and the host pool is empty (restores + finishes release both tiers).
    A swap-off engine builds no swapper at all — the default config is
    structurally identical to the single-tier engine."""
    _, swp, _ = trio_outputs
    alloc = swp.scheduler.allocator
    assert alloc.num_free == alloc.num_pages - 1
    assert swp.swapper.host.num_in_use == 0
    assert not swp.scheduler.swapped
    off = _mk(num_pages=8)          # no generate: construction is cheap
    assert off.swapper is None and off.scheduler.swapper is None
    assert not CacheConfig().kv_swap_enabled
    assert CacheConfig(swap_space_gb=0.5).kv_swap_enabled


def test_swap_metrics_and_trace(trio_outputs):
    """/metrics carries the two-tier series (kind-labeled preemptions, swap
    page counters, latency histogram, host-pool gauges) and the trace ring
    carries kind-tagged preempt events plus swap events with page counts."""
    from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics

    _, swp, _ = trio_outputs
    text = Metrics(swp).render()
    by_kind = swp.scheduler.num_preemptions_by_kind
    assert ('kgct_preemptions_total{kind="swap"} %d'
            % by_kind["swap"]) in text
    assert 'kgct_preemptions_total{kind="recompute"} 0' in text
    out_pages = swp.obs.swap_pages["out"]
    assert out_pages > 0 and swp.obs.swap_pages["in"] == out_pages
    assert f"kgct_kv_swap_out_pages_total {out_pages}" in text
    assert f"kgct_kv_swap_in_pages_total {out_pages}" in text
    assert "kgct_kv_swap_seconds_bucket" in text
    assert ("kgct_kv_host_pages_total %d"
            % swp.swapper.host.num_pages) in text
    assert "kgct_kv_host_pages_in_use 0" in text
    assert "kgct_num_swapped 0" in text
    events = swp.obs.tracer.events()
    swaps = [e for e in events if e.kind == "swap"]
    assert swaps and all(e.args["pages"] > 0 and e.args["dir"] in ("out", "in")
                         for e in swaps)
    assert sum(e.args["pages"] for e in swaps if e.args["dir"] == "out") \
        == out_pages
    preempts = [e for e in events if e.kind == "preempt"]
    assert preempts and all(e.args["preempt_kind"] == "swap"
                            for e in preempts)
    # resume events fire on restoration (preempt_count > 0 readmission)
    assert any(e.kind == "resume" for e in events)
    # a swap-off engine renders the same families as zeros (nan-free fresh
    # scrape, dashboards need no existence check); no generate — cheap
    text0 = Metrics(_mk(num_pages=8)).render()
    assert "kgct_kv_host_pages_total 0" in text0
    assert "kgct_kv_swap_out_pages_total 0" in text0


def test_prefix_spill_second_chance():
    """An evicted prefix-cache entry spills to host; a later lookup restores
    it (host hit) instead of re-prefilling, and the continuation matches the
    first run exactly."""
    rng = np.random.default_rng(7)
    shared = rng.integers(1, 500, 16).tolist()         # 2 full pages
    params = SamplingParams(max_tokens=4, temperature=0.0)
    eng = _mk(num_pages=9, swap_gb=0.05, max_seqs=2, prefix=True,
              max_prefill=64)
    pc = eng.scheduler.prefix_cache
    out1 = eng.generate([shared + [7, 7]], params)[0]
    assert len(pc._entries) == 2 and not pc._host_entries
    # Unique-prompt churn forces the CachingPageAllocator to evict the
    # shared entries — with the host tier attached they spill, not drop.
    for _ in range(3):
        eng.generate([rng.integers(1, 500, 16).tolist() + [3]], params)
    assert pc._host_entries, "eviction never spilled to host"
    out2 = eng.generate([shared + [7, 7]], params)[0]
    assert pc.host_hits > 0, "second-chance host hit never fired"
    assert out1.output_token_ids == out2.output_token_ids
    # metrics surface the restore counter
    from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics
    assert ("kgct_prefix_cache_host_hits_total %d"
            % pc.host_hits) in Metrics(eng).render()


# -- scheduler-level lifecycle (no device work: FakeSwapper) -----------------

class FakeHost:
    def __init__(self, num_pages=64):
        self.num_pages = num_pages
        self.num_free = num_pages

    @property
    def num_in_use(self):
        return self.num_pages - self.num_free


class FakeSwapper:
    def __init__(self, fail_out=False, fail_in=False):
        self.host = FakeHost()
        self.fail_out = fail_out
        self.fail_in = fail_in
        self.freed_host: list = []
        self.swapped_in: list = []
        self._next = 1000

    def swap_out(self, pages, request_id=""):
        if self.fail_out:
            raise RuntimeError("injected swap-out failure")
        hps = list(range(self._next, self._next + len(pages)))
        self._next += len(pages)
        self.host.num_free -= len(pages)
        return hps

    def swap_in(self, host_pages, device_pages, request_id=""):
        if self.fail_in:
            raise RuntimeError("injected swap-in failure")
        self.swapped_in.append((list(host_pages), list(device_pages)))
        self.host.num_free += len(host_pages)

    def free_host(self, host_pages):
        self.freed_host.extend(host_pages)
        self.host.num_free += len(host_pages)

    def notify_restored(self, seq):
        pass


def _sched_cfg(num_pages=3, page_size=2, max_num_seqs=4):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages),
        scheduler=SchedulerConfig(max_num_seqs=max_num_seqs,
                                  max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(16, 32, 64),
                                  decode_window=1))


def _pressure_pair(swapper):
    """Two 1-page sequences on a 2-usable-page pool, both needing a second
    page — the TestPreemptionInDecode shape, with a swapper attached."""
    sched = Scheduler(_sched_cfg(), 3)
    sched.attach_swapper(swapper)
    a = Sequence("a", [1, 2], SamplingParams(max_tokens=64))
    b = Sequence("b", [3, 4], SamplingParams(max_tokens=64))
    sched.add(a)
    sched.add(b)
    assert sched.schedule().kind == "prefill"
    a.append_token(5)
    b.append_token(6)
    return sched, a, b


def test_scheduler_preempts_by_swap_and_state_survives():
    fake = FakeSwapper()
    sched, a, b = _pressure_pair(fake)
    prefilled_before = b.num_prefilled
    batch = sched.schedule()
    assert batch.kind == "decode"
    assert [s.request_id for s in batch.seqs] == ["a"]
    assert sched.num_preemptions_by_kind == {"recompute": 0, "swap": 1}
    assert list(sched.swapped) == [b] and not sched.waiting
    assert b.status == SequenceStatus.PREEMPTED
    assert b.host_pages and not b.pages
    # chunk progress / prefix-lookup state survive swap (vs recompute reset)
    assert b.num_prefilled == prefilled_before
    assert sched.has_work()
    # a finishes -> pages free -> next schedule restores b into running
    sched.finish(a, None)
    batch = sched.schedule()
    assert batch is not None and batch.kind == "decode"
    assert [s.request_id for s in batch.seqs] == ["b"]
    assert b.status == SequenceStatus.RUNNING
    assert b.pages and not b.host_pages
    assert fake.swapped_in and fake.host.num_in_use == 0


def test_scheduler_swap_out_failure_degrades_to_recompute():
    fake = FakeSwapper(fail_out=True)
    sched, a, b = _pressure_pair(fake)
    batch = sched.schedule()
    assert batch.kind == "decode"           # never wedges the step
    assert sched.num_preemptions_by_kind == {"recompute": 1, "swap": 0}
    assert not sched.swapped and sched.waiting[0] is b
    assert b.num_prefilled == 0 and not b.host_pages


def test_scheduler_swap_in_failure_degrades_to_recompute():
    fake = FakeSwapper()
    sched, a, b = _pressure_pair(fake)
    sched.schedule()                        # b swap-preempted
    fake.fail_in = True
    sched.finish(a, None)
    batch = sched.schedule()
    # restore failed: b fell back to the recompute queue with its host copy
    # dropped and progress reset — and the SAME schedule call re-admitted
    # it as a full re-prefill (the pool is empty now), never wedging.
    assert not sched.swapped and not b.host_pages
    assert batch is not None and batch.kind == "prefill"
    assert [s.request_id for s in batch.seqs] == ["b"]
    assert b.status == SequenceStatus.RUNNING
    assert fake.freed_host
    # the preemption is RECLASSIFIED: the recovery that actually happened
    # was a recompute re-prefill, and the kind-labeled counter is the
    # operator's swap-sizing signal
    assert sched.num_preemptions_by_kind == {"recompute": 1, "swap": 0}


def test_unrestorable_swapped_sequence_degrades_to_recompute():
    """A swapped sequence whose committed+window page need exceeds TOTAL
    pool capacity can never pass the restore gate (num_tokens is frozen
    while swapped) — it must fall back to the recompute waiting queue,
    where the admission capacity machinery owns the outcome, instead of
    pinning schedule() in a forever-None loop (review finding)."""
    fake = FakeSwapper()
    cfg = _sched_cfg()          # 2 usable pages
    cfg = EngineConfig(
        model=cfg.model, cache=cfg.cache,
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(16, 32, 64),
                                  decode_window=6))
    sched = Scheduler(cfg, 3)
    sched.attach_swapper(fake)
    a = Sequence("a", [1, 2], SamplingParams(max_tokens=64))
    b = Sequence("b", [3, 4], SamplingParams(max_tokens=64))
    sched.add(a)
    sched.add(b)
    assert sched.schedule().kind == "prefill"
    a.append_token(5)
    b.append_token(6)
    # window=6 => both rows want ceil((2+6)/2)=4 pages > 2 usable; growth
    # preempts b by swap, then a (sole survivor) still cannot cover its own
    # window and self-preempts: schedule() returns None this round.
    assert sched.schedule() is None
    assert sched.num_preemptions_by_kind["swap"] == 2
    # Next schedule: both swapped heads are permanently unrestorable (want
    # 4 > pool 2) — they must degrade to recompute readmission, re-prefill
    # (the pool fits cdiv(3,2)=2 pages), and progress resumes.
    batch = sched.schedule()
    assert not sched.swapped
    assert batch is not None and batch.kind == "prefill"
    assert not b.host_pages and not a.host_pages
    assert fake.host.num_in_use == 0
    # both preemptions reclassified: the recoveries were recomputes
    assert sched.num_preemptions_by_kind == {"recompute": 2, "swap": 0}


def test_abort_swapped_sequence_frees_host_pages():
    fake = FakeSwapper()
    sched, a, b = _pressure_pair(fake)
    sched.schedule()
    hps = list(b.host_pages)
    assert sched.abort("b")
    assert b.is_finished and not b.host_pages
    assert fake.freed_host == hps and fake.host.num_in_use == 0
    assert not sched.has_work() or sched.running


@pytest.mark.slow
def test_sanitizer_accepts_swap_churn(monkeypatch):
    """KGCT_SANITIZE=1 + swap churn: the KV-slot shadow treats swapped-in
    slots as committed history — no false positives (SanitizerError) across
    a full preempt/restore cycle. Greedy-only (the shadow is position-based
    and sampling-agnostic); slow-tier: it builds its own engine (the env
    var is read at construction) and tier-1 headroom is nearly spent."""
    monkeypatch.setenv("KGCT_SANITIZE", "1")
    eng = _mk(num_pages=8, swap_gb=0.05)
    assert eng._sanitizer is not None
    outs = eng.generate(_PROMPTS,
                        SamplingParams(max_tokens=16, temperature=0.0))
    assert eng.scheduler.num_preemptions_by_kind["swap"] > 0
    assert eng._sanitizer.checks > 0
    assert [o.finished for o in outs] == [True] * 3


@pytest.mark.slow
def test_swap_soak_oversubscribed_sessions():
    """Soak: 8 greedy sessions on a ~2x-oversubscribed pool churn through
    repeated swap-preempt/restore cycles; outputs stay byte-identical to an
    unpressured engine and both tiers drain to empty."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 500, 24).tolist() for _ in range(8)]
    params = SamplingParams(max_tokens=24, temperature=0.0)
    big = _mk(num_pages=256, max_seqs=8)
    ref = big.generate(prompts, params)
    del big
    eng = _mk(num_pages=25, swap_gb=0.1, max_seqs=8)   # ~half the demand
    outs = eng.generate(prompts, params)
    assert eng.scheduler.num_preemptions_by_kind["swap"] >= 2
    for a, b in zip(ref, outs):
        assert a.output_token_ids == b.output_token_ids
    alloc = eng.scheduler.allocator
    assert alloc.num_free == alloc.num_pages - 1
    assert eng.swapper.host.num_in_use == 0
