"""Per-rule positive/negative pins for the kgct-lint rule suite.

Every rule must (a) fire on a minimal violating snippet — the regression
the rule exists to catch — and (b) stay silent on the idiomatic-correct
form the engine actually uses. The empty-baseline run over the real
package is tests/test_lint_clean.py; these are the rule semantics.
"""

import textwrap
from pathlib import Path

import pytest

from kubernetes_gpu_cluster_tpu.analysis.core import LintModule, run_lint
from kubernetes_gpu_cluster_tpu.analysis.rules import ALL_RULES, rules_by_code


def lint(code: str, rule_code: str, relpath: str = "engine/fake.py"):
    mod = LintModule(Path(relpath), source=textwrap.dedent(code))
    [rule] = rules_by_code([rule_code])
    return list(rule.check(mod))


class TestTraceSafety:  # KGCT001
    def test_python_if_on_traced_arg_fires(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """, "KGCT001")
        assert len(found) == 1 and "if" in found[0].message

    def test_taint_propagates_through_assignment(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                y = x + 1
                while y < 3:
                    y = y + 1
                return y
        """, "KGCT001")
        assert found and "while" in found[0].message

    def test_builder_maybe_jit_pattern_is_analyzed(self):
        found = lint("""
            class FooEngine:
                def _build_step(self):
                    def step(params, kv, flags):
                        return kv if bool(flags) else params
                    return self._maybe_jit(step, donate_argnums=(1,))
        """, "KGCT001")
        # both the conditional expression and the bool() call flag
        assert found and any("bool()" in f.message for f in found)

    def test_shape_len_and_static_argnames_stay_silent(self):
        assert lint("""
            import jax

            def build():
                def step(x, mode):
                    n = x.shape[0]
                    m = n if n % 2 == 0 else 1
                    if mode == "greedy":
                        return x.reshape(m, -1)
                    if len(x) > 4:
                        return x * 2
                    return x
                return jax.jit(step, static_argnames=("mode",))
        """, "KGCT001") == []


class TestHostSync:  # KGCT002
    def test_item_in_hot_path_fires(self):
        found = lint("""
            class FooEngine:
                def step(self):
                    out = self._decode_fn(1)
                    return out.item()
        """, "KGCT002")
        assert len(found) == 1 and ".item()" in found[0].message

    def test_reachability_through_self_calls(self):
        found = lint("""
            class FooEngine:
                def _step(self):
                    return self._helper()

                def _helper(self):
                    x = self._decode_fn(1)
                    x.block_until_ready()
                    return x
        """, "KGCT002")
        assert found and "block_until_ready" in found[0].message

    def test_implicit_float_on_step_output_fires(self):
        found = lint("""
            class FooEngine:
                def _step(self):
                    out = self._decode_fn(1)
                    return float(out)
        """, "KGCT002")
        assert found and "float()" in found[0].message

    def test_device_fetch_window_is_sanctioned(self):
        assert lint("""
            class FooEngine:
                def _step(self):
                    out = self._decode_fn(1)
                    with ph("device_fetch"):
                        out.block_until_ready()
                    return out
        """, "KGCT002") == []

    def test_off_hot_path_sync_is_fine(self):
        # probe/bench code outside step reachability may sync freely
        assert lint("""
            class FooEngine:
                def probe(self):
                    self._decode_fn(1).block_until_ready()
        """, "KGCT002") == []


class TestRecompileRisk:  # KGCT003
    def test_jit_in_loop_fires(self):
        found = lint("""
            import jax

            def bench(xs):
                for x in xs:
                    f = jax.jit(lambda a: a + 1)
                    f(x)
        """, "KGCT003")
        assert found and "loop" in found[0].message

    def test_jit_in_hot_path_fires(self):
        found = lint("""
            import jax

            class FooEngine:
                def _step(self, fn, x):
                    return jax.jit(fn)(x)
        """, "KGCT003")
        assert found and "hot-path" in found[0].message

    def test_unbucketed_len_shape_fires(self):
        found = lint("""
            import numpy as np

            class FooEngine:
                def _step(self, seqs):
                    return self._decode_fn(np.zeros((len(seqs), 4)))
        """, "KGCT003")
        assert found and "bucket" in found[0].message

    def test_bucketed_len_and_init_builders_stay_silent(self):
        assert lint("""
            import jax
            import numpy as np

            class FooEngine:
                def _build_decode_fn(self):
                    def step(x):
                        return x
                    return jax.jit(step)

                def _step(self, seqs):
                    B = _bucket(len(seqs), self.buckets)
                    return self._decode_fn(np.zeros((B, 4)))
        """, "KGCT003") == []


class TestDonationSafety:  # KGCT004
    def test_read_after_donation_fires(self):
        found = lint("""
            import jax

            class FooEngine:
                def __init__(self, step):
                    self._step_fn = jax.jit(step, donate_argnums=(1,))

                def run(self, params, kv):
                    out = self._step_fn(params, kv)
                    return out, kv.sum()
        """, "KGCT004")
        assert len(found) == 1 and "donated buffer 'kv'" in found[0].message

    def test_rebound_in_call_statement_is_safe(self):
        assert lint("""
            import jax

            class FooEngine:
                def __init__(self, step):
                    self._step_fn = jax.jit(step, donate_argnums=(1,))

                def run(self, params):
                    out, self.kv = self._step_fn(params, self.kv)
                    return out, self.kv.sum()
        """, "KGCT004") == []

    def test_builder_indirection_is_resolved(self):
        found = lint("""
            class FooEngine:
                def __init__(self):
                    self._step_fn = self._build()

                def _build(self):
                    def step(params, kv):
                        return kv
                    return self._maybe_jit(step, donate_argnums=(1,))

                def run(self, params, kv):
                    out = self._step_fn(params, kv)
                    norm = kv.mean()
                    return out, norm
        """, "KGCT004")
        assert found and "read after dispatch" in found[0].message


class TestKVCommitSafety:  # KGCT005
    def test_naked_slot_math_fires(self):
        found = lint("""
            def compute_slot(page, ps, pos):
                return page * ps + pos % ps
        """, "KGCT005", relpath="engine/spec/fake.py")
        assert len(found) == 1 and "slot expression" in found[0].message

    def test_scrap_page_guard_is_enough(self):
        assert lint("""
            def compute_slot(page, ps, pos, max_len):
                if pos >= max_len:
                    return SCRAP_PAGE * ps + pos % ps
                return page * ps + pos % ps
        """, "KGCT005", relpath="engine/spec/fake.py") == []

    def test_committed_anchor_is_enough(self):
        assert lint("""
            def fill_row(seq, slot_mapping, ps):
                pos = seq.num_tokens - 1
                slot_mapping[0] = seq.pages[pos // ps] * ps + pos % ps
        """, "KGCT005", relpath="engine/fake.py") == []

    def test_out_of_scope_modules_ignored(self):
        assert lint("""
            def compute_slot(page, ps, pos):
                return page * ps + pos % ps
        """, "KGCT005", relpath="serving/fake.py") == []


class TestAsyncioHygiene:  # KGCT006
    def test_time_sleep_in_async_fires(self):
        found = lint("""
            import time

            async def handler(request):
                time.sleep(0.5)
        """, "KGCT006")
        assert found and "time.sleep" in found[0].message

    def test_get_event_loop_fires_anywhere(self):
        found = lint("""
            import asyncio

            def start(self):
                self._loop = asyncio.get_event_loop()
        """, "KGCT006")
        assert found and "get_running_loop" in found[0].message

    def test_sync_context_and_async_sleep_are_fine(self):
        assert lint("""
            import asyncio
            import time

            def worker():
                time.sleep(0.5)

            async def handler(request):
                await asyncio.sleep(0.5)
                loop = asyncio.get_running_loop()
        """, "KGCT006") == []


class TestMetricHygiene:  # KGCT007
    def test_request_scope_construction_fires(self):
        found = lint("""
            async def handler(request):
                h = Histogram("kgct_x_seconds")
                h.observe(1.0)
        """, "KGCT007")
        assert found and "process-lifetime" in found[0].message

    def test_unbounded_label_value_fires(self):
        found = lint("""
            def on_finish(self, seq):
                self.ttft.observe(0.5, (seq.request_id,))
        """, "KGCT007")
        assert found and "unbounded" in found[0].message

    def test_fstring_label_fires(self):
        found = lint("""
            def on_finish(self, seq, code):
                self.ttft.observe(0.5, (f"status-{code}",))
        """, "KGCT007")
        assert found and "unbounded" in found[0].message

    def test_init_construction_and_bounded_labels_are_fine(self):
        assert lint("""
            class Obs:
                def __init__(self):
                    self.ttft = Histogram("kgct_ttft_seconds",
                                          labels=("outcome",))

                def on_finish(self, seq, outcome):
                    self.ttft.observe(0.5, (_outcome(seq, None),))
        """, "KGCT007") == []


class TestLoggingHygiene:  # KGCT008
    def test_fstring_log_fires(self):
        found = lint("""
            def step(logger, arr):
                logger.info(f"step done: {arr}")
        """, "KGCT008")
        assert found and "f-string" in found[0].message

    def test_eager_percent_and_format_fire(self):
        found = lint("""
            def step(logger, arr):
                logger.debug("x: %s" % arr)
                logger.warning("y: {}".format(arr))
        """, "KGCT008")
        assert len(found) == 2

    def test_lazy_template_is_fine(self):
        assert lint("""
            def step(logger, arr):
                logger.info("step done: %s tokens", arr)
        """, "KGCT008") == []


class TestQuantSurface:  # KGCT009
    def test_direct_matmul_on_quant_key_fires(self):
        found = lint("""
            import jax.numpy as jnp

            def attn(x, lp):
                return jnp.dot(x, lp["wq"], preferred_element_type=None)
        """, "KGCT009", relpath="models/fake.py")
        assert len(found) == 1 and "_dot" in found[0].message

    def test_matmul_operator_spelling_fires(self):
        found = lint("""
            def attn(x, lp):
                return x @ lp["wo"]
        """, "KGCT009", relpath="models/fake.py")
        assert len(found) == 1 and "matmul" in found[0].message

    def test_astype_dequant_copy_fires(self):
        found = lint("""
            import jax.numpy as jnp

            def upload(lp, dtype):
                return lp["w_down"].astype(dtype)
        """, "KGCT009", relpath="models/fake.py")
        assert len(found) == 1 and "dequantizes" in found[0].message

    def test_sanctioned_dot_helper_is_silent(self):
        assert lint("""
            import jax.numpy as jnp

            def _dot(x, lp, name):
                w = lp[name]
                if w.dtype == jnp.int8:
                    return jnp.dot(x, w.astype(x.dtype)) * lp[name + "_scale"]
                return jnp.dot(x, w)

            def attn(x, lp):
                return _dot(x, lp, "wq")
        """, "KGCT009", relpath="models/fake.py") == []

    def test_non_quant_keys_and_other_modules_silent(self):
        code = """
            import jax.numpy as jnp

            def route(x, lp):
                return jnp.dot(x, lp["router"])
        """
        assert lint(code, "KGCT009", relpath="models/fake.py") == []
        # outside models/: out of scope entirely
        assert lint("""
            import jax.numpy as jnp

            def f(x, lp):
                return jnp.dot(x, lp["wq"])
        """, "KGCT009", relpath="engine/fake.py") == []

    def test_key_literal_drift_fires(self):
        found = lint("""
            QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up",
                                "w_down", "router")
        """, "KGCT009", relpath="ops/quant.py")
        assert len(found) == 1 and "drifted" in found[0].message

    def test_real_surface_is_in_sync(self):
        """The shipped ops/quant.py literal matches the rule's pin (the
        tier-1 empty-baseline run enforces this too; this pin keeps the
        failure local and explicit)."""
        root = Path(__file__).resolve().parent.parent
        mod = LintModule(
            root / "kubernetes_gpu_cluster_tpu" / "ops" / "quant.py",
            root=root / "kubernetes_gpu_cluster_tpu")
        [rule] = rules_by_code(["KGCT009"])
        assert list(rule.check(mod)) == []


class TestSwapOrder:  # KGCT010
    def test_release_before_gather_fires(self):
        found = lint("""
            def preempt(self, victim):
                self._release(victim)
                pages = self.swapper.swap_out(victim.pages)
                victim.host_pages = pages
        """, "KGCT010")
        assert len(found) == 1 and "before the swap gather" in found[0].message

    def test_allocator_free_before_spill_fires(self):
        found = lint("""
            def evict(self, page):
                self.allocator.free([page])
                return self.swapper.spill_page(page)
        """, "KGCT010")
        assert len(found) == 1

    def test_gather_then_release_is_silent(self):
        assert lint("""
            def preempt(self, victim):
                pages = self.swapper.swap_out(victim.pages)
                self._release(victim)
                victim.host_pages = pages
        """, "KGCT010") == []

    def test_release_only_and_host_free_silent(self):
        # abort/finish paths release without gathering — out of scope
        assert lint("""
            def abort(self, seq):
                self._release(seq)
        """, "KGCT010") == []
        # host-pool frees are not device releases
        assert lint("""
            def drop(self, page, hp):
                self.swapper.free_host([hp])
                return self.swapper.spill_page(page)
        """, "KGCT010") == []

    def test_outside_engine_out_of_scope(self):
        assert lint("""
            def preempt(self, victim):
                self._release(victim)
                return self.swapper.swap_out(victim.pages)
        """, "KGCT010", relpath="serving/fake.py") == []


class TestRouterPickPath:  # KGCT011
    def test_min_over_replicas_outside_pick_fires(self):
        found = lint("""
            class Router:
                def proxy(self, request):
                    replica = min(self.replicas, key=lambda r: r.inflight)
                    return replica
        """, "KGCT011", relpath="serving/fake.py")
        assert len(found) == 1 and "_pick seam" in found[0].message

    def test_sorted_inflight_selection_fires(self):
        found = lint("""
            def rebalance(self, healthy):
                return sorted(healthy, key=lambda r: r.inflight)[0]
        """, "KGCT011", relpath="serving/fake.py")
        assert len(found) == 1

    def test_random_choice_from_replicas_fires(self):
        found = lint("""
            import random

            def desperate(self):
                return random.choice(self.replicas)
        """, "KGCT011", relpath="serving/fake.py")
        assert len(found) == 1

    def test_inflight_mutation_outside_proxy_fires(self):
        found = lint("""
            def metrics(self, replica):
                replica.inflight = 0
                return replica
        """, "KGCT011", relpath="serving/fake.py")
        assert len(found) == 1 and "accounting pair" in found[0].message

    def test_pick_and_proxy_accounting_are_sanctioned(self):
        assert lint("""
            class Router:
                def _pick(self, exclude=None):
                    healthy = [r for r in self.replicas if r.healthy]
                    least = min(r.inflight for r in healthy)
                    tied = [r for r in healthy if r.inflight == least]
                    return tied[0]

                def proxy(self, request):
                    replica = self._pick()
                    replica.inflight += 1
                    try:
                        return self.forward(replica, request)
                    finally:
                        replica.inflight -= 1
        """, "KGCT011", relpath="serving/fake.py") == []

    def test_reads_and_init_stay_silent(self):
        # health/metrics ITERATE and read the load signal — not selection.
        assert lint("""
            class Replica:
                def __init__(self, url):
                    self.inflight = 0

            class Router:
                def health(self, request):
                    return {r.url: r.inflight for r in self.replicas}

                def metrics(self, request):
                    total = sum(r.inflight for r in self.replicas)
                    return total
        """, "KGCT011", relpath="serving/fake.py") == []

    def test_outside_serving_out_of_scope(self):
        assert lint("""
            def schedule(self):
                victim = min(self.replicas, key=lambda r: r.inflight)
                victim.inflight += 1
        """, "KGCT011", relpath="engine/fake.py") == []


class TestTraceEmitHygiene:  # KGCT012
    def test_file_io_in_emit_fires(self):
        found = lint("""
            class RequestTracer:
                def emit(self, kind, request_id=""):
                    with open("/tmp/trace.log", "a") as f:
                        f.write(kind)
        """, "KGCT012", relpath="observability/fake.py")
        assert found and any("open()" in f.message for f in found)

    def test_serialization_and_lock_in_record_fire(self):
        found = lint("""
            import json

            class FlightRecorder:
                def record(self, kind, request_id="", args=None):
                    with self._lock:
                        self._ring.append(json.dumps(args))
        """, "KGCT012", relpath="observability/fake.py")
        msgs = " ".join(f.message for f in found)
        assert "json.dumps" in msgs and "lock held" in msgs

    def test_host_sync_in_snapshot_fires(self):
        found = lint("""
            class FlightRecorder:
                def maybe_snapshot(self):
                    self._ring.append(self._occupancy.item())
        """, "KGCT012", relpath="observability/fake.py")
        assert len(found) == 1 and ".item()" in found[0].message

    def test_dump_in_engine_hot_path_fires(self):
        found = lint("""
            class FooEngine:
                def step(self):
                    outs = self._run()
                    self.obs.flight.dump("per_step")
                    return outs

                def _run(self):
                    return []
        """, "KGCT012", relpath="engine/fake.py")
        assert len(found) == 1 and "hot-path" in found[0].message

    def test_export_in_router_proxy_fires(self):
        found = lint("""
            class Router:
                async def proxy(self, request):
                    doc = self.tracer.export_perfetto()
                    return doc
        """, "KGCT012", relpath="serving/fake.py")
        assert len(found) == 1 and "export" in found[0].message

    def test_awaited_emit_in_serving_fires(self):
        found = lint("""
            class Router:
                async def proxy(self, request):
                    await self.tracer.emit("arrival", "r1")
        """, "KGCT012", relpath="serving/fake.py")
        assert len(found) == 1 and "synchronous" in found[0].message

    def test_append_only_writes_and_offline_dump_are_silent(self):
        # The shipped shape: emit/record are pure appends; dump/export live
        # on failure handlers and debug endpoints, off the hot path.
        assert lint("""
            import time

            class RequestTracer:
                def emit(self, kind, request_id="", **args):
                    rec = self.recorder
                    if rec is not None:
                        rec.record(kind, request_id, args)
                    self._ring.append((time.monotonic(), kind, args))

            class FlightRecorder:
                def record(self, kind, request_id="", args=None):
                    self._ring.append((time.monotonic(), kind, args))

                def maybe_snapshot(self):
                    self._ring.append(self._source())

                def dump(self, reason):
                    with open("/tmp/x.json", "w") as f:
                        f.write(reason)
        """, "KGCT012", relpath="observability/fake.py") == []

    def test_emit_on_hot_path_and_dump_off_it_are_silent(self):
        # Emitting from step IS the design; dump from a non-step method
        # (failure handler) is the sanctioned place for I/O.
        assert lint("""
            class FooEngine:
                def step(self):
                    self.obs.tracer.emit("decode", "", batch=4)
                    return []

                def on_fatal(self, err):
                    self.obs.flight.dump("fatal", error=str(err))
        """, "KGCT012", relpath="engine/fake.py") == []

    def test_outside_scopes_silent(self):
        # dump on a non-proxy serving handler (debug endpoint): fine.
        assert lint("""
            class Router:
                async def debug_flightrecorder(self, request):
                    return self.flight.export()
        """, "KGCT012", relpath="serving/fake.py") == []
        # unrelated .dump() with no tracer/recorder receiver: out of scope.
        assert lint("""
            class FooEngine:
                def step(self):
                    return self.checkpointer.dump("state")
        """, "KGCT012", relpath="engine/fake.py") == []


class TestFramework:
    def test_every_rule_has_code_name_description(self):
        codes = [r.code for r in ALL_RULES]
        assert len(codes) == len(set(codes)) and len(codes) >= 8
        for rule in ALL_RULES:
            assert rule.code.startswith("KGCT")
            assert rule.name and rule.description

    def test_unknown_select_raises(self):
        with pytest.raises(ValueError, match="unknown rule"):
            rules_by_code(["KGCT999"])

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        findings = run_lint([bad])
        assert len(findings) == 1 and findings[0].rule == "KGCT000"

    def test_findings_sorted_and_formatted(self, tmp_path):
        f = tmp_path / "two.py"
        f.write_text(textwrap.dedent("""
            import time

            async def b(logger, arr):
                time.sleep(1)
                logger.info(f"x {arr}")
        """))
        findings = run_lint([f], root=tmp_path)
        assert [x.rule for x in findings] == ["KGCT006", "KGCT008"]
        assert findings[0].format().startswith("two.py:")


class TestKVBoundary:  # KGCT013
    def test_np_asarray_of_kv_pool_fires(self):
        found = lint("""
            import numpy as np

            class Engine:
                def leak(self, pages):
                    return np.asarray(self.kv_cache.k[:, pages])
        """, "KGCT013", relpath="engine/engine.py")
        assert len(found) == 1 and "sanctioned" in found[0].message

    def test_device_get_of_kv_fires_in_serving(self):
        found = lint("""
            import jax

            def ship(kv):
                return jax.device_get(kv.k)
        """, "KGCT013", relpath="serving/api_server.py")
        assert len(found) == 1

    def test_kv_cache_module_is_the_sanctioned_seam(self):
        """The seam's own gather (np.asarray of the fetched KV inside
        kv_cache.py) is exempt — it IS the sanctioned path."""
        assert lint("""
            import numpy as np

            class KVPageIO:
                def export_pages(self, pages):
                    k_g, v_g = self._gather_fn(self.kv.k, self.kv.v, pages)
                    return np.asarray(k_g), np.asarray(v_g)
        """, "KGCT013", relpath="engine/kv_cache.py") == []

    def test_non_kv_fetches_stay_silent(self):
        assert lint("""
            import numpy as np

            def fine(batch, next_tokens, seq):
                a = np.asarray(next_tokens)
                b = np.asarray(batch.tokens)
                c = np.asarray(seq.pages, np.int64)
                return a, b, c
        """, "KGCT013", relpath="engine/engine.py") == []


class TestSwapOrderExportCoverage:  # KGCT010 extension
    def test_free_before_export_gather_fires(self):
        found = lint("""
            class Engine:
                def export_held(self, seq):
                    self.scheduler.allocator.free(seq.pages)
                    return self.kv_io.export_pages(seq.pages)
        """, "KGCT010", relpath="engine/engine.py")
        assert len(found) == 1 and "before" in found[0].message

    def test_gather_then_free_is_clean(self):
        assert lint("""
            class Engine:
                def export_held(self, seq):
                    k, v = self.kv_io.export_pages(seq.pages)
                    self.scheduler.allocator.free(seq.pages)
                    return k, v
        """, "KGCT010", relpath="engine/engine.py") == []


class TestMigrationStateSafety:  # KGCT014
    def test_inflight_window_in_returned_dict_fires(self):
        """The regression the rule exists to catch: window speculation
        (sampled-but-unfetched device tokens) serialized into the
        cross-replica state — a peer importing it forks the stream from
        history this engine never committed."""
        found = lint("""
            class Engine:
                def export_running(self, seq):
                    return {
                        "output_token_ids": list(seq.output_token_ids)
                        + list(self._inflight["toks"]),
                        "k": self.kv_io.export_pages(seq.pages),
                    }
        """, "KGCT014", relpath="engine/engine.py")
        assert len(found) == 1 and "_inflight" in found[0].message
        assert "committed" in found[0].message

    def test_window_scratch_store_into_state_fires(self):
        found = lint("""
            class Engine:
                def _export_state(self, seq, k_np, v_np):
                    state = {"k": k_np, "v": v_np}
                    state["logprobs"] = self._window_scratch.float_b
                    return state
        """, "KGCT014", relpath="engine/engine.py")
        assert len(found) == 1 and "float_b" in found[0].message

    def test_zombie_set_via_update_fires(self):
        found = lint("""
            class Engine:
                def export_running(self, seq):
                    state = {}
                    state.update(pending=self._inflight["zombies"])
                    return state
        """, "KGCT014", relpath="engine/engine.py")
        assert len(found) == 1

    def test_committed_only_export_with_zombie_bookkeeping_silent(self):
        """The idiomatic export: committed host history + fetched buffers
        into the state; the in-flight window touched ONLY for retirement
        bookkeeping (zombie registration, deferred release) — data and
        bookkeeping must be distinguished or the real export can never
        pass its own rule."""
        assert lint("""
            class Engine:
                def export_running(self, seq):
                    k_np, v_np = self.kv_io.export_pages(seq.pages)
                    state = {
                        "prompt_token_ids": list(seq.prompt_token_ids),
                        "output_token_ids": list(seq.output_token_ids),
                        "output_logprobs": list(seq.output_logprobs),
                        "k": k_np, "v": v_np,
                    }
                    state["mid_stream"] = True
                    if self._inflight is not None:
                        self._inflight["zombies"].add(seq.request_id)
                        self._deferred_release.append(seq)
                    return state
        """, "KGCT014", relpath="engine/engine.py") == []

    def test_non_export_functions_silent(self):
        assert lint("""
            class Engine:
                def step(self):
                    toks = self._inflight["window_toks"]
                    return {"window": toks}
        """, "KGCT014", relpath="engine/engine.py") == []

    def test_outside_engine_scope_silent(self):
        assert lint("""
            def export_running(seq, inflight):
                return {"toks": inflight["window_toks"]}
        """, "KGCT014", relpath="serving/api_server.py") == []


class TestTenantAccountingSafety:  # KGCT015
    def test_serving_layer_charge_fires(self):
        """The regression the rule exists to catch: a serving handler
        charging a tier's fairness clock 'to help' a tenant — every
        subsequent weighted-fair decision is then skewed for the life of
        the process."""
        found = lint("""
            class APIServer:
                async def _run(self, request, tier):
                    self.engine.scheduler.qos.charge(tier, 512)
        """, "KGCT015", relpath="serving/api_server.py")
        assert len(found) == 1 and "fair-share seam" in found[0].message

    def test_direct_clock_write_outside_qos_fires(self):
        found = lint("""
            def rebalance(qos):
                qos.virtual_tokens["batch"] += 100.0
        """, "KGCT015", relpath="engine/engine.py")
        assert len(found) == 1 and "virtual_tokens" in found[0].message

    def test_sync_active_from_bench_fires(self):
        found = lint("""
            def warm(engine):
                engine.scheduler.qos.sync_active(["interactive"])
        """, "KGCT015", relpath="observability/__init__.py")
        assert len(found) == 1

    def test_scheduler_seam_charge_silent(self):
        assert lint("""
            class Scheduler:
                def _qos_charge_batch(self, batch):
                    for seq in batch.seqs:
                        self.qos.charge(seq.params.qos_tier, 8)
        """, "KGCT015", relpath="engine/scheduler.py") == []

    def test_mixed_batch_seam_silent(self):
        assert lint("""
            def build_mixed_batch(sched):
                sched.qos.charge("batch", 1)
        """, "KGCT015", relpath="engine/mixed_batch.py") == []

    def test_clock_write_inside_qos_module_silent(self):
        assert lint("""
            class QoSAccounting:
                def charge(self, name, tokens):
                    self.virtual_tokens[name] += tokens / 2.0
                    self.served_tokens[name] += tokens
        """, "KGCT015", relpath="engine/qos.py") == []

    def test_reads_and_other_accounting_silent(self):
        """Snapshot READS and the serving-side admission ledger
        (tier_inflight — a different mechanism with its own accounting
        pair) stay silent."""
        assert lint("""
            def render(qos, adm):
                vt = dict(qos.virtual_tokens)
                adm.tier_inflight["batch"] += 1
                return vt
        """, "KGCT015", relpath="serving/metrics.py") == []


class TestFleetFetchBoundary:  # KGCT016
    def test_handler_side_import_fires(self):
        """A serving handler calling an import seam directly on the event
        loop — the scatter would race the step loop against the donated
        pool."""
        found = lint("""
            class Handler:
                async def fetch(self, request):
                    state = decode(await request.read())
                    self.engine.engine.import_request("r", [1], None, state)
        """, "KGCT016", relpath="serving/api_server.py")
        assert len(found) == 1 and "worker" in found[0].message

    def test_worker_wrapped_import_silent(self):
        assert lint("""
            class Handler:
                async def fetch(self, request):
                    state = decode(await request.read())
                    await self.engine.run_in_worker(
                        lambda e: e.import_request("r", [1], None, state))
        """, "KGCT016", relpath="serving/api_server.py") == []

    def test_streamed_chunk_scatter_outside_worker_fires(self):
        found = lint("""
            async def pull(engine, dec, data):
                for ck, cv in dec.feed(data):
                    engine.import_prefix_chunk("h", ck, cv)
        """, "KGCT016", relpath="serving/api_server.py")
        assert found and "import_prefix_chunk" in found[0].message

    def test_post_to_worker_cleanup_silent(self):
        assert lint("""
            def cleanup(self, handle):
                self.engine.post_to_worker(
                    lambda e: e.abort_prefix_import(handle))
        """, "KGCT016", relpath="serving/api_server.py") == []

    def test_kv_cache_rebind_fires(self):
        found = lint("""
            def f(engine, kv):
                engine.kv_cache = kv
        """, "KGCT016", relpath="serving/router.py")
        assert found and "kv_cache" in found[0].message

    def test_engine_modules_out_of_scope(self):
        """The engine package IS the seam's home; the rule polices only
        serving-side entry points."""
        assert lint("""
            def f(self, state):
                self.import_request("r", [1], None, state)
        """, "KGCT016", relpath="engine/engine.py") == []

    def test_async_engine_worker_loop_exempt(self):
        """The worker loop executes the seam by definition — it is the
        other side of run_in_worker, not a bypass."""
        assert lint("""
            def _worker(self):
                self.engine.import_request("r", [1], None, {})
        """, "KGCT016", relpath="serving/async_engine.py") == []


class TestDraftStateBoundary:  # KGCT017
    def test_direct_draft_kv_reach_fires(self):
        found = lint("""
            def step(self):
                kv = self.scheduler.spec_proposer.kv_cache
        """, "KGCT017", relpath="engine/engine.py")
        assert len(found) == 1 and "kv_cache" in found[0].message

    def test_alias_then_allocator_reach_fires(self):
        """A local alias of the proposer handle must not launder the
        reach: taint follows simple assignments."""
        found = lint("""
            def grow(sched):
                proposer = sched.spec_proposer
                pages = proposer.allocator.allocate(2)
        """, "KGCT017", relpath="engine/scheduler.py")
        assert len(found) == 1 and "allocator" in found[0].message

    def test_attr_assignment_through_handle_fires(self):
        found = lint("""
            def tune(sched):
                sched.spec_proposer.k = 8
        """, "KGCT017", relpath="engine/scheduler.py")
        assert len(found) == 1

    def test_draft_params_rebind_fires(self):
        found = lint("""
            def swap_weights(self, params):
                self.scheduler.spec_proposer.params = params
        """, "KGCT017", relpath="engine/engine.py")
        assert len(found) >= 1

    def test_proposer_seam_silent(self):
        """Installation + the seam methods (propose_batch/retain/k/
        compiled_variants) are the sanctioned surface."""
        assert lint("""
            def build(self, config, seqs):
                self.scheduler.spec_proposer = build_draft_runner(config)
                self.scheduler.spec_proposer.retain(ids)
                drafts = self.scheduler.spec_proposer.propose_batch(seqs, 4)
                k = self.scheduler.spec_proposer.k
                proposer = self.scheduler.spec_proposer
                if hasattr(proposer, "compiled_variants"):
                    n = proposer.compiled_variants()
        """, "KGCT017", relpath="engine/engine.py") == []

    def test_spec_package_is_the_implementation(self):
        """engine/spec/ OWNS the state — the rule polices reaches from
        outside, not the implementation itself."""
        assert lint("""
            def _grow(self, row):
                self.kv_cache = self.allocator.allocate(1)
                row.pages = self.spec_proposer.kv_cache
        """, "KGCT017", relpath="engine/spec/draft_model.py") == []

    def test_outside_engine_scope_silent(self):
        assert lint("""
            def f(e):
                kv = e.scheduler.spec_proposer.kv_cache
        """, "KGCT017", relpath="serving/api_server.py") == []


class TestWireIntegrity:  # KGCT018
    def test_unverified_commit_fires(self):
        found = lint("""
            async def fleet_import(self, handle):
                await self.engine.run_in_worker(
                    lambda e: e.commit_prefix_import(handle))
        """, "KGCT018", relpath="serving/api_server.py")
        assert len(found) == 1 and "checksum-verify" in found[0].message

    def test_unverified_import_request_fires(self):
        found = lint("""
            async def restore(self, rid, ids, params, state):
                await self.engine.run_in_worker(
                    lambda e: e.import_request(rid, ids, params, state))
        """, "KGCT018", relpath="serving/api_server.py")
        assert len(found) == 1

    def test_unverified_resume_import_fires(self):
        found = lint("""
            def resume(self, rid, ids, params, parked):
                return self.engine.generate(rid, ids, params,
                                            handoff=parked)
        """, "KGCT018", relpath="serving/api_server.py")
        assert len(found) == 1 and "generate" in found[0].message

    def test_verify_in_same_function_silent(self):
        assert lint("""
            def resume(self, rid, ids, params, parked):
                verify_import_state(parked)
                return self.engine.generate(rid, ids, params,
                                            handoff=parked)
        """, "KGCT018", relpath="serving/api_server.py") == []

    def test_verify_in_transitive_callee_silent(self):
        """The reaching path follows intra-module helpers: the pull
        helper's verifying decode covers the caller's commit."""
        assert lint("""
            async def _pull(self, url, rid):
                data = await fetch(url)
                state = decode_handoff(data, require_integrity=True)
                return state

            async def run(self, rid, ids, params, url):
                handoff = await self._pull(url, rid)
                return self.engine.generate(rid, ids, params,
                                            handoff=handoff)
        """, "KGCT018", relpath="serving/api_server.py") == []

    def test_decoder_construction_counts_as_verify(self):
        assert lint("""
            async def _pull_prefix(self, resp, handle):
                dec = PrefixStreamDecoder(require_integrity=True)
                async for chunk in resp:
                    dec.feed(chunk)
                await self.engine.run_in_worker(
                    lambda e: e.commit_prefix_import(handle))
        """, "KGCT018", relpath="serving/api_server.py") == []

    def test_handoff_none_generate_silent(self):
        """The plain serve path (no wire state) is not a commit."""
        assert lint("""
            def run(self, rid, ids, params):
                return self.engine.generate(rid, ids, params,
                                            handoff=None)
        """, "KGCT018", relpath="serving/api_server.py") == []

    def test_raw_frombuffer_fires(self):
        found = lint("""
            import numpy as np

            def decode(data):
                return np.frombuffer(data, dtype=np.uint8)
        """, "KGCT018", relpath="serving/api_server.py")
        assert len(found) == 1 and "frombuffer" in found[0].message

    def test_codec_and_worker_loop_exempt(self):
        assert lint("""
            import numpy as np

            def decode(data):
                return np.frombuffer(data, dtype=np.uint8)
        """, "KGCT018", relpath="serving/handoff.py") == []
        assert lint("""
            def _drain_inbox(self, e, rid, ids, params, state):
                e.import_request(rid, ids, params, state)
        """, "KGCT018", relpath="serving/async_engine.py") == []

    def test_outside_serving_silent(self):
        assert lint("""
            def commit(self, handle):
                self.commit_prefix_import(handle)
        """, "KGCT018", relpath="engine/engine.py") == []


class TestAwaitAtomicity:  # KGCT019
    def test_guard_await_claim_fires(self):
        found = lint("""
            class H:
                async def admit(self, rid, req):
                    if rid not in self._active:
                        ok = await self.check(req)
                        self._active[rid] = ok
        """, "KGCT019", relpath="serving/api_server.py")
        assert len(found) == 1 and "_active" in found[0].message

    def test_mutator_claim_after_await_fires(self):
        found = lint("""
            class H:
                async def track(self, rid):
                    if rid not in self._mid_stream:
                        await self._announce(rid)
                        self._mid_stream.add(rid)
        """, "KGCT019", relpath="serving/api_server.py")
        assert len(found) == 1 and "_mid_stream" in found[0].message

    def test_is_none_guard_with_await_in_claim_fires(self):
        # The double-create shape: both callers pass `is None`, both await
        # the constructor, the second overwrites (and leaks) the first.
        found = lint("""
            class H:
                async def session(self):
                    if self._http is None:
                        self._http = await make_session()
                    return self._http
        """, "KGCT019", relpath="serving/api_server.py")
        assert len(found) == 1

    def test_no_await_between_guard_and_claim_silent(self):
        # Check-then-act with nothing interleaved IS atomic on the loop —
        # the real _pull_prefix lazy-session shape.
        assert lint("""
            class H:
                async def session(self, req):
                    if self._http is None:
                        self._http = make_session()
                    await self._http.post(req)
        """, "KGCT019", relpath="serving/api_server.py") == []

    def test_while_recheck_guard_silent(self):
        # A while re-evaluates its condition after every await: the
        # condition-variable idiom, no stale-guard window.
        assert lint("""
            class H:
                async def wait_slot(self, rid):
                    while rid in self._active:
                        await asyncio.sleep(0)
                    self._active[rid] = True
        """, "KGCT019", relpath="serving/api_server.py") == []

    def test_sync_reservation_seam_silent(self):
        # The declared atomic-reservation seam: a sync def cannot suspend,
        # so check-and-claim cannot race itself on the loop.
        assert lint("""
            class E:
                def reserve_request_id(self, rid):
                    if rid in self._queues:
                        return False
                    self._queues[rid] = make_queue()
                    self._reserved.add(rid)
                    return True
        """, "KGCT019", relpath="serving/async_engine.py") == []

    def test_outside_serving_silent(self):
        assert lint("""
            class H:
                async def admit(self, rid, req):
                    if rid not in self._active:
                        ok = await self.check(req)
                        self._active[rid] = ok
        """, "KGCT019", relpath="engine/fake.py") == []


class TestThreadOwnership:  # KGCT020
    def test_iteration_through_alias_fires(self):
        found = lint("""
            class S:
                async def scrape(self):
                    sched = self.engine.engine.scheduler
                    return [r.request_id for r in sched.running]
        """, "KGCT020", relpath="serving/api_server.py")
        assert len(found) == 1 and "iterates" in found[0].message

    def test_method_call_on_owned_state_fires(self):
        found = lint("""
            class S:
                async def compact(self):
                    self.engine.engine.scheduler.preempt_lowest()
        """, "KGCT020", relpath="serving/api_server.py")
        assert len(found) == 1 and "calls a method" in found[0].message

    def test_subscript_fires(self):
        found = lint("""
            class S:
                async def peek(self):
                    eng = self.engine.engine
                    return eng.scheduler.waiting[0]
        """, "KGCT020", relpath="serving/api_server.py")
        assert len(found) == 1 and "subscripts" in found[0].message

    def test_rebind_fires(self):
        found = lint("""
            class S:
                async def reset(self):
                    self.engine.engine.scheduler = None
        """, "KGCT020", relpath="serving/api_server.py")
        assert len(found) == 1 and "rebinds" in found[0].message

    def test_gil_atomic_snapshots_silent(self):
        # The /healthz queue-depth gauges: len()/truthiness/is-None read
        # one reference atomically and copy nothing mutable.
        assert lint("""
            class S:
                async def health(self):
                    sched = self.engine.engine.scheduler
                    depth = len(sched.waiting) + len(sched.running)
                    ok = bool(depth) if sched.swapped is None else True
                    if sched.waiting:
                        depth += 1
                    return depth
        """, "KGCT020", relpath="serving/api_server.py") == []

    def test_worker_op_seam_silent(self):
        assert lint("""
            class S:
                async def depth(self):
                    return await self.engine.run_in_worker(
                        lambda e: [r.request_id for r in e.scheduler.running])
        """, "KGCT020", relpath="serving/api_server.py") == []

    def test_sync_setup_silent(self):
        # __init__ runs before the worker thread exists.
        assert lint("""
            class S:
                def __init__(self, engine):
                    kv = engine.engine.kv_cache
                    self.pages = kv.num_pages()
        """, "KGCT020", relpath="serving/api_server.py") == []

    def test_async_engine_module_exempt(self):
        assert lint("""
            class A:
                async def drain(self):
                    self.engine.scheduler.abort_all()
        """, "KGCT020", relpath="serving/async_engine.py") == []

    def test_outside_serving_silent(self):
        assert lint("""
            class S:
                async def scrape(self):
                    return [r for r in self.engine.engine.scheduler.running]
        """, "KGCT020", relpath="engine/fake.py") == []


class TestLockDiscipline:  # KGCT021
    def test_await_under_lock_fires(self):
        found = lint("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                async def flush(self):
                    with self._lock:
                        await self._send()
        """, "KGCT021", relpath="serving/api_server.py")
        assert len(found) == 1 and "await while holding" in found[0].message

    def test_blocking_under_loop_contended_lock_fires(self):
        # The indirect stall: the worker sleeps under a lock an async
        # handler also acquires — the handler blocks the WHOLE loop in
        # acquire() for the sleep's duration.
        found = lint("""
            import threading, time

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                async def touch(self):
                    with self._lock:
                        self.n += 1

                def worker_side(self):
                    with self._lock:
                        time.sleep(1.0)
        """, "KGCT021", relpath="serving/api_server.py")
        assert len(found) == 1 and "time.sleep" in found[0].message

    def test_cross_boundary_lock_fires_at_both_sites(self):
        found = lint("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    with self._lock:
                        self.q.append(1)

                async def submit(self, x):
                    with self._lock:
                        self.q.append(x)
        """, "KGCT021", relpath="serving/api_server.py")
        assert len(found) == 2
        assert all("both sides" in f.message for f in found)

    def test_worker_only_lock_over_blocking_send_silent(self):
        # The directive leader's shape: the lock serializes the worker and
        # heartbeat threads; no event-loop code ever contends for it, so
        # blocking sends under it stall nobody's loop.
        assert lint("""
            import threading, time

            class L:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    with self._lock:
                        time.sleep(0.1)

                def heartbeat(self):
                    with self._lock:
                        time.sleep(0.1)
        """, "KGCT021", relpath="serving/multihost.py") == []

    def test_handshake_module_exempt_from_cross_boundary(self):
        # AsyncLLMEngine._cv IS the sanctioned loop/worker handshake.
        assert lint("""
            import threading

            class A:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._thread = threading.Thread(target=self._worker)

                def _worker(self):
                    with self._cv:
                        self._cv.wait()

                async def generate(self, item):
                    with self._cv:
                        self._inbox.append(item)
                        self._cv.notify()
        """, "KGCT021", relpath="serving/async_engine.py") == []

    def test_condition_wait_not_blocking_set(self):
        # wait/wait_for RELEASE the lock while waiting — the handshake
        # idiom is not a blocking call under the lock.
        found = lint("""
            import threading

            class A:
                def __init__(self):
                    self._cv = threading.Condition()

                async def poke(self):
                    with self._cv:
                        self._cv.notify()

                def worker(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self.ready)
        """, "KGCT021", relpath="serving/fake.py")
        assert found == []
