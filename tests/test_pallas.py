"""Pallas kernels vs the XLA reference implementations — NUMERICS ONLY.

These run in interpret mode on the CPU mesh and cannot catch Mosaic
compile-time failures (round-2 postmortem). The on-chip compile gates are:
benchmarks/tpu_kernel_check.py (manual, compile + numerics on the real
chip), the engine's init-time probe compile with XLA fallback
(engine.LLMEngine._probe_pallas_compile), and __graft_entry__.entry() which
builds its step with use_pallas=True on TPU for the driver's compile check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.ops.attention import (
    paged_decode_attention_xla, ragged_prefill_attention_xla)
from kubernetes_gpu_cluster_tpu.ops.pallas.flash_prefill import flash_ragged_prefill
from kubernetes_gpu_cluster_tpu.ops.pallas.paged_decode import pallas_paged_decode


class TestPagedDecodeKernel:
    @pytest.mark.parametrize("nh,nkv,hd,ps", [(4, 2, 32, 8), (8, 8, 64, 16)])
    def test_matches_xla(self, nh, nkv, hd, ps):
        B, P, pps = 4, 9, 3
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((B, nh, hd)), jnp.float32)
        k_pool = jnp.asarray(rng.standard_normal((P, ps, nkv * hd)), jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((P, ps, nkv * hd)), jnp.float32)
        k_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        v_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        page_tables = jnp.asarray(
            rng.permutation(np.arange(1, 1 + B * pps)).reshape(B, pps), jnp.int32)
        # Heterogeneous contexts incl. ctx=1 (empty pool) and a padding row.
        context_lens = jnp.asarray([1, ps + 2, 2 * ps, 0], jnp.int32)

        ref = paged_decode_attention_xla(q, k_pool, v_pool, page_tables,
                                         context_lens, k_cur, v_cur, 0.125)
        got = pallas_paged_decode(q, k_pool, v_pool, page_tables,
                                  context_lens, k_cur, v_cur, 0.125,
                                  interpret=True)
        # Padding row (ctx=0) is garbage in both paths; compare real rows.
        np.testing.assert_allclose(np.asarray(got)[:3], np.asarray(ref)[:3],
                                   rtol=2e-5, atol=2e-5)

    def test_stacked_pool_layer_index(self):
        B, P, ps, nkv, nh, hd, pps, L = 2, 5, 8, 2, 4, 32, 2, 3
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.standard_normal((B, nh, hd)), jnp.float32)
        pool_k = jnp.asarray(rng.standard_normal((L, P, ps, nkv * hd)), jnp.float32)
        pool_v = jnp.asarray(rng.standard_normal((L, P, ps, nkv * hd)), jnp.float32)
        k_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        v_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        pt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        cl = jnp.asarray([ps + 1, 5], jnp.int32)
        for layer in range(L):
            ref = paged_decode_attention_xla(q, pool_k[layer], pool_v[layer],
                                             pt, cl, k_cur, v_cur, 0.2)
            got = pallas_paged_decode(q, pool_k, pool_v, pt, cl, k_cur, v_cur,
                                      0.2, layer=layer, interpret=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)


class TestFlashPrefillKernel:
    @pytest.mark.parametrize("T,block", [(64, 16), (128, 128)])
    def test_matches_xla(self, T, block):
        nh, nkv, hd = 4, 2, 32
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        # Three segments + trailing padding.
        lens = [T // 4, T // 3, T // 4]
        seg = np.full(T, -1, np.int32)
        pos = np.zeros(T, np.int32)
        i = 0
        for s, n in enumerate(lens):
            seg[i:i+n] = s
            pos[i:i+n] = np.arange(n)
            i += n
        seg_ids = jnp.asarray(seg)
        positions = jnp.asarray(pos)

        ref = ragged_prefill_attention_xla(q, k, v, seg_ids, positions, 0.125)
        got = flash_ragged_prefill(q, k, v, seg_ids, positions, 0.125,
                                   block_q=block, block_k=block, interpret=True)
        real = seg >= 0
        np.testing.assert_allclose(np.asarray(got)[real], np.asarray(ref)[real],
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_head_mapping(self):
        """Each q head must read its own kv head (h // g), not head 0."""
        T, nh, nkv, hd = 32, 4, 4, 32   # distinct kv per q head
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        seg_ids = jnp.zeros(T, jnp.int32)
        positions = jnp.arange(T, dtype=jnp.int32)
        ref = ragged_prefill_attention_xla(q, k, v, seg_ids, positions, 0.2)
        got = flash_ragged_prefill(q, k, v, seg_ids, positions, 0.2,
                                   block_q=16, block_k=16, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestInt4MatmulKernel:
    """W4A16 dequant-fused matmul kernel (ops/pallas/int4_matmul.py) vs the
    XLA fusion path and the explicit dequant reference — interpret mode
    (the on-chip compile gate is benchmarks/tpu_kernel_check.py)."""

    @pytest.mark.parametrize("K,N,gs", [(512, 256, 128), (256, 128, 64)])
    def test_matches_dequant_reference(self, K, N, gs):
        from kubernetes_gpu_cluster_tpu.ops.pallas.int4_matmul import (
            pallas_int4_matmul)
        from kubernetes_gpu_cluster_tpu.ops.quant import (int4_matmul_xla,
                                                          quantize_tensor_int4,
                                                          unpack_int4)
        T = 5
        rng = np.random.default_rng(7)
        w = rng.standard_normal((K, N)).astype(np.float32)
        x = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
        packed, scale = quantize_tensor_int4(w, gs)
        deq = (unpack_int4(packed).astype(np.float32)
               .reshape(K // gs, gs, N) * scale[:, None, :]).reshape(K, N)
        ref = np.asarray(x) @ deq
        got = pallas_int4_matmul(x, jnp.asarray(packed), jnp.asarray(scale),
                                 interpret=True)
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5, atol=2e-4)
        # and the XLA fusion path agrees with the same reference
        xla = int4_matmul_xla(x, jnp.asarray(packed), jnp.asarray(scale))
        np.testing.assert_allclose(np.asarray(xla), ref, rtol=2e-5, atol=2e-4)

    def test_unaligned_dims_fall_back_to_xla(self):
        """Non-128-multiple N must not compute a wrong padded edge: the
        wrapper falls back to the XLA path (documented in the wrapper)."""
        from kubernetes_gpu_cluster_tpu.ops.pallas.int4_matmul import (
            pallas_int4_matmul)
        from kubernetes_gpu_cluster_tpu.ops.quant import (int4_matmul_xla,
                                                          quantize_tensor_int4)
        rng = np.random.default_rng(8)
        K, N, gs = 128, 96, 64                  # N % 128 != 0
        w = rng.standard_normal((K, N)).astype(np.float32)
        x = jnp.asarray(rng.standard_normal((3, K)), jnp.float32)
        packed, scale = quantize_tensor_int4(w, gs)
        got = pallas_int4_matmul(x, jnp.asarray(packed), jnp.asarray(scale))
        ref = int4_matmul_xla(x, jnp.asarray(packed), jnp.asarray(scale))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref))


class TestPallasUnderMesh:
    """The shard_map tp wrappers (ops.attention.*_tp): kernel-under-mesh
    semantics on the 8-device CPU mesh in interpret mode. The on-chip gate
    for this path is the engine's per-shard probe compile
    (LLMEngine._probe_pallas_compile(tp))."""

    def test_paged_decode_tp_matches_oracle(self):
        from kubernetes_gpu_cluster_tpu.ops.attention import (
            paged_decode_attention_tp)
        from kubernetes_gpu_cluster_tpu.parallel import make_mesh

        mesh = make_mesh(tp=2, dp=4)
        B, P, ps, nkv, nh, hd, pps, L = 4, 9, 8, 2, 4, 32, 3, 2
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.standard_normal((B, nh, hd)), jnp.float32)
        pool_k = jnp.asarray(rng.standard_normal((L, P, ps, nkv * hd)), jnp.float32)
        pool_v = jnp.asarray(rng.standard_normal((L, P, ps, nkv * hd)), jnp.float32)
        k_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        v_cur = jnp.asarray(rng.standard_normal((B, nkv, hd)), jnp.float32)
        pt = jnp.asarray(rng.permutation(np.arange(1, 1 + B * pps)).reshape(B, pps),
                         jnp.int32)
        cl = jnp.asarray([1, ps + 2, 2 * ps, 3], jnp.int32)
        for layer in range(L):
            ref = paged_decode_attention_xla(q, pool_k[layer], pool_v[layer],
                                             pt, cl, k_cur, v_cur, 0.125)
            got = paged_decode_attention_tp(mesh, q, pool_k, pool_v, pt, cl,
                                            k_cur, v_cur, 0.125, layer=layer,
                                            interpret=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)

    def test_flash_prefill_tp_matches_oracle(self):
        from kubernetes_gpu_cluster_tpu.ops.attention import (
            ragged_prefill_attention_tp)
        from kubernetes_gpu_cluster_tpu.parallel import make_mesh

        mesh = make_mesh(tp=2)
        T, nh, nkv, hd = 64, 4, 2, 32
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        seg = np.concatenate([np.full(30, 0), np.full(20, 1), np.full(14, -1)])
        pos = np.concatenate([np.arange(30), np.arange(20), np.zeros(14)])
        seg = jnp.asarray(seg, jnp.int32)
        pos = jnp.asarray(pos, jnp.int32)
        ref = ragged_prefill_attention_xla(q, k, v, seg, pos, 0.125)
        got = ragged_prefill_attention_tp(mesh, q, k, v, seg, pos, 0.125,
                                          interpret=True)
        mask = np.asarray(seg) >= 0
        np.testing.assert_allclose(np.asarray(got)[mask], np.asarray(ref)[mask],
                                   rtol=2e-5, atol=2e-5)

    def test_engine_decode_via_attn_mesh(self):
        """Full forward_decode with attn_mesh set (the engine's GSPMD + Pallas
        path) must match the plain XLA forward. interpret-mode Pallas inside
        the real model forward, under jit, on the tp=2 mesh."""
        import functools

        from kubernetes_gpu_cluster_tpu.config import (CacheConfig,
                                                       get_model_config)
        from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
        from kubernetes_gpu_cluster_tpu.models import llama as model_lib
        from kubernetes_gpu_cluster_tpu.parallel import make_mesh
        from kubernetes_gpu_cluster_tpu.parallel.sharding import (
            kv_cache_sharding, param_shardings)
        import kubernetes_gpu_cluster_tpu.ops.attention as attn

        cfg = get_model_config("debug-tiny")
        mesh = make_mesh(tp=2, dp=4)
        params = model_lib.init_params(cfg, jax.random.key(0))
        kv = allocate_kv_cache(cfg, CacheConfig(page_size=8, num_pages=17), 17)

        B, pps = 2, 2
        meta = model_lib.DecodeMeta(
            positions=jnp.asarray([5, 3], jnp.int32),
            slot_mapping=jnp.asarray([1 * 8 + 5, 3 * 8 + 3], jnp.int32),
            page_tables=jnp.asarray([[1, 2], [3, 4]], jnp.int32),
            context_lens=jnp.asarray([6, 4], jnp.int32))
        tokens = jnp.asarray([7, 11], jnp.int32)

        ref, _, _ = model_lib.forward_decode(params, cfg, tokens, meta, kv,
                                             use_pallas=False)

        # Route the tp wrapper's kernel through interpret mode (CPU mesh).
        orig = attn.paged_decode_attention_tp
        def tp_interp(mesh_, *a, **kw):
            return orig(mesh_, *a, **{**kw, "interpret": True})
        attn.paged_decode_attention_tp = tp_interp
        model_lib.paged_decode_attention_tp = tp_interp
        try:
            sharded_params = jax.device_put(params, param_shardings(mesh, cfg))
            sharded_kv = jax.tree.map(
                functools.partial(jax.device_put,
                                  device=kv_cache_sharding(mesh, cfg)), kv)
            got, _, _ = jax.jit(
                lambda p, k: model_lib.forward_decode(p, cfg, tokens, meta, k,
                                                      attn_mesh=mesh)
            )(sharded_params, sharded_kv)
        finally:
            attn.paged_decode_attention_tp = orig
            model_lib.paged_decode_attention_tp = orig
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestFlashPrefillHistory:
    """flash_prefill_history vs prefill_history_attention_xla — the chunked
    prefill kernel (history pages streamed via page-table index maps + flat
    causal chunk phase)."""

    def _mk(self, T, hist_len, nh=4, nkv=2, hd=32, ps=8, pps=4, L=2,
            pad=0, seed=0):
        from kubernetes_gpu_cluster_tpu.ops.attention import (
            prefill_history_attention_xla)
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
        seg = jnp.asarray(
            np.where(np.arange(T) < T - pad, 0, -1), jnp.int32)
        pos = jnp.asarray(
            np.where(np.arange(T) < T - pad,
                     hist_len + np.arange(T), 0), jnp.int32)
        pool_k = jnp.asarray(
            rng.standard_normal((L, 1 + pps, ps, nkv * hd)), jnp.float32)
        pool_v = jnp.asarray(
            rng.standard_normal((L, 1 + pps, ps, nkv * hd)), jnp.float32)
        pt = jnp.asarray(1 + np.arange(pps), jnp.int32)
        return (q, k, v, seg, pos, pool_k, pool_v, pt,
                jnp.asarray(hist_len, jnp.int32), hd ** -0.5,
                prefill_history_attention_xla)

    @pytest.mark.parametrize("T,hist_len,pad", [
        (16, 0, 0),     # first chunk: no history at all
        (16, 13, 0),    # partial page history
        (16, 32, 4),    # full pages + tail padding
        (32, 20, 7),    # multi-qblock with blocks smaller than T
    ])
    def test_matches_xla(self, T, hist_len, pad):
        from kubernetes_gpu_cluster_tpu.ops.pallas.flash_prefill_hist import (
            flash_prefill_history)
        (q, k, v, seg, pos, pk, pv, pt, hl, scale, oracle) = self._mk(
            T, hist_len, pad=pad)
        for layer in range(2):
            ref = oracle(q, k, v, seg, pos, pk, pv, pt, hl, scale,
                         layer=jnp.asarray(layer))
            got = flash_prefill_history(q, k, v, seg, pos, pk, pv, pt, hl,
                                        scale, layer=jnp.asarray(layer),
                                        block_q=8, block_k=8, interpret=True)
            mask = np.asarray(seg) >= 0
            np.testing.assert_allclose(np.asarray(got)[mask],
                                       np.asarray(ref)[mask],
                                       rtol=2e-5, atol=2e-5)

    def test_flat_pool_and_jit(self):
        """3-D (single-layer) pool path, under jit with a traced hist_len."""
        from kubernetes_gpu_cluster_tpu.ops.pallas.flash_prefill_hist import (
            flash_prefill_history)
        (q, k, v, seg, pos, pk, pv, pt, hl, scale, oracle) = self._mk(
            16, 11, pad=2, seed=3)
        ref = oracle(q, k, v, seg, pos, pk[0], pv[0], pt, hl, scale)
        fn = jax.jit(lambda *a: flash_prefill_history(
            *a, scale, block_q=8, block_k=8, interpret=True))
        got = fn(q, k, v, seg, pos, pk[0], pv[0], pt, hl)
        mask = np.asarray(seg) >= 0
        np.testing.assert_allclose(np.asarray(got)[mask],
                                   np.asarray(ref)[mask],
                                   rtol=2e-5, atol=2e-5)


def test_flash_prefill_partial_final_block():
    """T not a multiple of block_k: the partial final K/V block's padding is
    undefined memory (NaN in interpret mode) and must not poison real rows
    (regression: 0*NaN in the p@v contraction NaN'd the last q block)."""
    T, nh, nkv, hd = 300, 4, 2, 32
    rng = np.random.default_rng(9)
    q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
    seg = jnp.asarray(np.where(np.arange(T) < 280, 0, -1), jnp.int32)
    pos = jnp.asarray(np.where(np.arange(T) < 280, np.arange(T), 0), jnp.int32)
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, 0.125)
    got = flash_ragged_prefill(q, k, v, seg, pos, 0.125, interpret=True)
    mask = np.asarray(seg) >= 0
    assert np.isfinite(np.asarray(got)[mask]).all()
    np.testing.assert_allclose(np.asarray(got)[mask], np.asarray(ref)[mask],
                               rtol=2e-5, atol=2e-5)


def test_prefill_history_tp_matches_oracle():
    """The hist-kernel tp wrapper (chunked prefill under GSPMD meshes):
    interpret parity on the CPU tp=2 mesh vs the XLA oracle."""
    from kubernetes_gpu_cluster_tpu.ops.attention import (
        prefill_history_attention_tp, prefill_history_attention_xla)
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh

    mesh = make_mesh(tp=2, dp=4)
    T, nh, nkv, hd, ps, pps, L = 16, 4, 2, 32, 8, 4, 2
    hist_len = 13
    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, nkv, hd)), jnp.float32)
    seg = jnp.asarray(np.where(np.arange(T) < T - 3, 0, -1), jnp.int32)
    pos = jnp.asarray(np.where(np.arange(T) < T - 3,
                               hist_len + np.arange(T), 0), jnp.int32)
    pk = jnp.asarray(rng.standard_normal((L, 1 + pps, ps, nkv * hd)), jnp.float32)
    pv = jnp.asarray(rng.standard_normal((L, 1 + pps, ps, nkv * hd)), jnp.float32)
    pt = jnp.asarray(1 + np.arange(pps), jnp.int32)
    hl = jnp.asarray(hist_len, jnp.int32)
    for layer in range(L):
        ref = prefill_history_attention_xla(q, k, v, seg, pos, pk, pv, pt,
                                            hl, 0.125, layer=jnp.asarray(layer))
        got = prefill_history_attention_tp(mesh, q, k, v, seg, pos, pk, pv,
                                           pt, hl, 0.125,
                                           layer=jnp.asarray(layer),
                                           interpret=True)
        mask = np.asarray(seg) >= 0
        np.testing.assert_allclose(np.asarray(got)[mask],
                                   np.asarray(ref)[mask],
                                   rtol=2e-5, atol=2e-5)
