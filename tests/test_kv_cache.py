"""PageAllocator and KV cache pool tests (SURVEY C29 equivalent, native)."""

import pytest

from kubernetes_gpu_cluster_tpu.config import CacheConfig, get_model_config
from kubernetes_gpu_cluster_tpu.engine.kv_cache import (
    PageAllocator, allocate_kv_cache, derive_num_pages, kv_cache_bytes_per_page)


def test_allocator_basic():
    a = PageAllocator(num_pages=10, page_size=16)
    assert a.num_free == 9  # page 0 is scrap, never allocatable
    pages = a.allocate(3)
    assert len(pages) == 3 and 0 not in pages
    assert a.num_free == 6
    a.free(pages)
    assert a.num_free == 9


def test_allocator_exhaustion_and_double_free():
    a = PageAllocator(num_pages=4, page_size=8)
    pages = a.allocate(3)
    assert not a.can_allocate(1)
    with pytest.raises(RuntimeError, match="exhausted"):
        a.allocate(1)
    a.free(pages)
    with pytest.raises(RuntimeError, match="double free"):
        a.free(pages)


def test_allocator_refcount_fork():
    a = PageAllocator(num_pages=4, page_size=8)
    (p,) = a.allocate(1)
    a.fork(p)
    a.free([p])
    assert a.num_free == 2  # still held by the fork
    a.free([p])
    assert a.num_free == 3


def test_derive_num_pages_from_hbm_budget():
    model = get_model_config("debug-tiny")
    cache = CacheConfig(page_size=8)
    per_page = kv_cache_bytes_per_page(model, cache)
    n = derive_num_pages(model, cache, 512, 8, hbm_free_bytes=per_page * 100)
    assert n == 90  # 100 pages * 0.90 utilization
    # explicit override wins
    n = derive_num_pages(model, CacheConfig(page_size=8, num_pages=7), 512, 8)
    assert n == 7


def test_kv_cache_shape():
    model = get_model_config("debug-tiny")
    cache = CacheConfig(page_size=8)
    kv = allocate_kv_cache(model, cache, 16)
    assert kv.k.shape == (model.num_layers, 16, 8, model.num_kv_heads * model.head_dim)
    assert kv.num_pages == 16 and kv.page_size == 8
