"""The tier-1 lint gate: the FULL package is kgct-lint clean, no allowlist.

This is the enforcement half of the static-analysis subsystem: every rule
in analysis/rules runs over every package module (plus bench.py) and the
baseline is EMPTY. A hot-path host sync, a trace-unsafe branch, a donated
buffer read, an unbounded metric label — any regression fails here, in
tests, instead of shipping as a silent perf/correctness cliff. There is
deliberately no suppression mechanism: a finding is fixed or the rule is
wrong (and fixed).
"""

from pathlib import Path

from kubernetes_gpu_cluster_tpu.analysis import ALL_RULES, run_lint
from kubernetes_gpu_cluster_tpu.analysis.cli import main as lint_main

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kubernetes_gpu_cluster_tpu"
BENCH = REPO / "bench.py"


def test_package_is_lint_clean_empty_baseline():
    findings = run_lint([PACKAGE, BENCH], root=REPO)
    assert findings == [], (
        "kgct-lint must stay clean (fix the finding, don't allowlist):\n"
        + "\n".join(f.format() for f in findings))


def test_all_rules_actually_ran_against_real_structures():
    """Guard against a vacuous pass: the shared analyses must resolve the
    engine's real jitted programs, hot path and donation map — if a
    refactor renames the patterns the rules key on, this fails before the
    empty baseline becomes meaningless."""
    from kubernetes_gpu_cluster_tpu.analysis.core import LintModule
    mod = LintModule(PACKAGE / "engine" / "engine.py", root=REPO)
    jitted = {getattr(j.node, "name", "<lambda>")
              for j in mod.jitted_functions}
    assert {"prefill_step", "spec_step", "mixed_step"} <= jitted
    hot = {f.name for f in mod.hot_path_functions}
    assert {"step", "_step", "_step_spec", "_dispatch_window",
            "_process_window"} <= hot
    donated = mod.donated_attr_map
    assert donated.get("_prefill_fn") == (1,)
    assert donated.get("_decode_fn") == (1, 6)


def test_cli_clean_run_exits_zero(capsys):
    rc = lint_main([str(PACKAGE / "analysis")])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().err


def test_cli_findings_exit_one(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
    rc = lint_main([str(bad)])
    out = capsys.readouterr()
    assert rc == 1
    assert "KGCT006" in out.out


def test_cli_list_rules_shows_all_eight(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ALL_RULES:
        assert rule.code in out
    assert len(ALL_RULES) >= 8


def test_cli_json_format(tmp_path, capsys):
    import json
    bad = tmp_path / "bad.py"
    bad.write_text("def f(logger, a):\n    logger.info(f'{a}')\n")
    rc = lint_main([str(bad), "--format", "json"])
    findings = json.loads(capsys.readouterr().out)
    assert rc == 1 and findings[0]["rule"] == "KGCT008"


def test_cli_console_script_is_declared():
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('kgct-lint = "kubernetes_gpu_cluster_tpu.analysis.cli:main"'
            in pyproject)
