"""The tier-1 lint gate: the FULL package is kgct-lint clean, no allowlist.

This is the enforcement half of the static-analysis subsystem: every rule
in analysis/rules runs over every package module (plus bench.py) and the
baseline is EMPTY. A hot-path host sync, a trace-unsafe branch, a donated
buffer read, an unbounded metric label — any regression fails here, in
tests, instead of shipping as a silent perf/correctness cliff. There is
deliberately no suppression mechanism: a finding is fixed or the rule is
wrong (and fixed).
"""

from pathlib import Path

from kubernetes_gpu_cluster_tpu.analysis import ALL_RULES, run_lint
from kubernetes_gpu_cluster_tpu.analysis.cli import main as lint_main

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kubernetes_gpu_cluster_tpu"
BENCH = REPO / "bench.py"


def test_package_is_lint_clean_empty_baseline():
    findings = run_lint([PACKAGE, BENCH], root=REPO)
    assert findings == [], (
        "kgct-lint must stay clean (fix the finding, don't allowlist):\n"
        + "\n".join(f.format() for f in findings))


def test_all_rules_actually_ran_against_real_structures():
    """Guard against a vacuous pass: the shared analyses must resolve the
    engine's real jitted programs, hot path and donation map — if a
    refactor renames the patterns the rules key on, this fails before the
    empty baseline becomes meaningless."""
    from kubernetes_gpu_cluster_tpu.analysis.core import LintModule
    mod = LintModule(PACKAGE / "engine" / "engine.py", root=REPO)
    jitted = {getattr(j.node, "name", "<lambda>")
              for j in mod.jitted_functions}
    assert {"prefill_step", "spec_step", "mixed_step"} <= jitted
    hot = {f.name for f in mod.hot_path_functions}
    assert {"step", "_step", "_step_spec", "_dispatch_window",
            "_process_window"} <= hot
    donated = mod.donated_attr_map
    assert donated.get("_prefill_fn") == (1,)
    assert donated.get("_decode_fn") == (1, 6)


def test_cli_clean_run_exits_zero(capsys):
    rc = lint_main([str(PACKAGE / "analysis")])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().err


def test_cli_findings_exit_one(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
    rc = lint_main([str(bad)])
    out = capsys.readouterr()
    assert rc == 1
    assert "KGCT006" in out.out


def test_cli_list_rules_shows_all_eight(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ALL_RULES:
        assert rule.code in out
    assert len(ALL_RULES) >= 8


def test_cli_json_format(tmp_path, capsys):
    import json
    bad = tmp_path / "bad.py"
    bad.write_text("def f(logger, a):\n    logger.info(f'{a}')\n")
    rc = lint_main([str(bad), "--format", "json"])
    findings = json.loads(capsys.readouterr().out)
    assert rc == 1 and findings[0]["rule"] == "KGCT008"


def test_cli_console_script_is_declared():
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('kgct-lint = "kubernetes_gpu_cluster_tpu.analysis.cli:main"'
            in pyproject)


def test_concurrency_graph_resolves_real_seam_not_vacuous():
    """Guard against a vacuous pass for the interprocedural layer: the
    PackageModel over the real package must resolve the worker-op seam
    and at least one known async->engine path. An empty graph would make
    KGCT019-021's zero baseline meaningless — fail loudly here first."""
    from kubernetes_gpu_cluster_tpu.analysis.core import (
        CTX_LOOP, CTX_WORKER, PackageModel, get_module, iter_py_files)
    mods = [get_module(p, root=REPO) for p in iter_py_files([PACKAGE])]
    pm = PackageModel(mods)
    # The run_in_worker/post_to_worker seam resolves to real call sites.
    assert pm.seam_sites, "no worker-op seam sites resolved"
    assert any("serving/api_server.py" in rel
               for rel, _, _ in pm.seam_sites)
    # The seam's engine-method targets include the KV export/import ops.
    assert {"export_held", "import_request"} & set(pm.worker_op_targets)
    # At least one async def provably reaches engine state THROUGH the
    # seam (the sanctioned crossing the rules treat as legal).
    assert pm.async_engine_paths, "no async->engine path resolved"
    assert any("api_server" in caller
               for caller, _ in pm.async_engine_paths)
    # Context classification: the worker loop and the submit coroutine.
    ae = next(m for m in mods
              if m.relpath.replace("\\", "/").endswith(
                  "serving/async_engine.py"))
    assert CTX_WORKER in pm.contexts_of(ae, "AsyncLLMEngine._worker")
    assert CTX_LOOP in pm.contexts_of(ae, "AsyncLLMEngine.generate")
    # The engine's ONE sanctioned cross-boundary lock is seen as such.
    assert {CTX_LOOP, CTX_WORKER} <= pm.lock_contexts_of(ae, "_cv")
    # And an actually-empty graph is distinguishable (the loud-failure
    # property this test relies on).
    empty = PackageModel([])
    assert not empty.seam_sites and not empty.async_engine_paths


def test_module_cache_warm_run_parses_nothing():
    """The module-model cache: a warm re-run over unchanged files adds
    ZERO parses (pinned by parse count, not wall clock), and an edited
    file re-parses exactly once."""
    from kubernetes_gpu_cluster_tpu.analysis import core
    target = PACKAGE / "analysis"
    run_lint([target], root=REPO)           # prime (may hit prior cache)
    before = core.PARSE_COUNT
    warm = run_lint([target], root=REPO)
    assert core.PARSE_COUNT == before, (
        f"warm lint run re-parsed {core.PARSE_COUNT - before} file(s); "
        "the (path, content-hash) cache must make re-runs parse-free")
    assert warm == []


def test_module_cache_invalidates_on_content_change(tmp_path):
    from kubernetes_gpu_cluster_tpu.analysis import core
    f = tmp_path / "m.py"
    f.write_text("x = 1\n")
    m1 = core.get_module(f)
    assert core.get_module(f) is m1         # warm hit: same object
    f.write_text("x = 2\n")
    m2 = core.get_module(f)
    assert m2 is not m1                     # content hash changed
    assert core.get_module(f) is m2


def test_sarif_output_has_required_2_1_0_keys(tmp_path, capsys):
    """kgct-lint --format sarif validates against the SARIF 2.1.0
    required keys (what GitHub code-scanning ingestion checks)."""
    import json
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def h():\n    time.sleep(1)\n")
    artifact = tmp_path / "out.sarif"
    rc = lint_main([str(bad), "--format", "sarif",
                    "--sarif", str(artifact)])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert "$schema" in doc
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "kgct-lint"
    assert {r["id"] for r in driver["rules"]} == {
        r.code for r in ALL_RULES}
    result = run["results"][0]
    assert result["ruleId"] == "KGCT006"
    assert result["message"]["text"]
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 4
    # The --sarif artifact is the same document.
    assert json.loads(artifact.read_text()) == doc


def test_cli_changed_mode_lints_only_touched_files(capsys):
    """--changed HEAD in a clean tree lints nothing (and exits 0); the
    scope filter and git plumbing are exercised either way."""
    rc = lint_main([str(PACKAGE), "--changed", "HEAD"])
    assert rc in (0, 1)
    err = capsys.readouterr().err
    assert "finding(s)" in err
