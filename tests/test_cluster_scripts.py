"""Cluster bootstrap scripts: syntax + DRY_RUN golden-output tests.

The reference's bash was completely untested (SURVEY §4: "no automated
tests"); its failure modes were discovered on real machines and journaled.
These tests run every script in DRY_RUN mode (all state-changing commands go
through run() and print ``DRY: ...``) and assert the load-bearing behaviors
the reference got wrong first (reset ordering, the NO_PROXY cluster-CIDR
fix from old_README.md:659-684, the --cri-socket join append from
k8s_setup.sh:41-44) never regress.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

import pytest

SCRIPTS = pathlib.Path(__file__).resolve().parent.parent / "cluster" / "scripts"
ALL = sorted(SCRIPTS.glob("*.sh"))


def run_script(script: str, *args: str, env: dict | None = None) -> str:
    full_env = {"PATH": "/usr/bin:/bin:/usr/sbin:/sbin", "DRY_RUN": "1",
                "HOME": "/tmp", **(env or {})}
    r = subprocess.run(["bash", str(SCRIPTS / script), *args],
                       capture_output=True, text=True, env=full_env,
                       timeout=60)
    assert r.returncode == 0, f"{script} rc={r.returncode}\n{r.stderr}"
    return r.stdout + r.stderr


@pytest.mark.parametrize("script", ALL, ids=lambda p: p.name)
def test_bash_syntax(script):
    r = subprocess.run(["bash", "-n", str(script)], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("script", ALL, ids=lambda p: p.name)
def test_shellcheck(script):
    if shutil.which("shellcheck") is None:
        pytest.skip("shellcheck not installed")
    r = subprocess.run(["shellcheck", "-S", "error", str(script)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout


def test_node_setup_teardown_order():
    """Reset-first: kubelet stops and 6443 clears BEFORE state dirs vanish,
    and teardown runs before any install step (reference main() ordering,
    k8s_setup.sh:375-392)."""
    out = run_script("tpu_node_setup.sh", "--role=control_plane", "--yes")
    stop = out.index("DRY: systemctl stop kubelet")
    rm = out.index("DRY: rm -rf /etc/kubernetes")
    init = out.index("DRY: kubeadm init")
    assert stop < rm < init
    assert out.index("DRY: swapoff") < init
    assert "DRY: kubeadm reset -f" in out


def test_node_setup_no_proxy_covers_cluster_cidrs():
    """The hard-won fix: NO_PROXY must include pod AND service CIDRs or
    in-cluster traffic is sent to the egress proxy (old_README.md:659-684)."""
    out = run_script(
        "tpu_node_setup.sh", "--role=control_plane", "--yes",
        env={"HTTP_PROXY_URL": "http://127.0.0.1:8118",
             "POD_CIDR": "10.244.0.0/16", "SERVICE_CIDR": "10.96.0.0/12"})
    no_proxy = [l for l in out.splitlines() if "NO_PROXY=" in l]
    assert no_proxy, out
    line = no_proxy[0]
    for needed in ("10.244.0.0/16", "10.96.0.0/12", ".svc", ".cluster.local",
                   "localhost"):
        assert needed in line, f"NO_PROXY missing {needed}: {line}"


def test_node_setup_join_appends_cri_socket():
    """--join without --cri-socket gets the socket appended
    (reference k8s_setup.sh:41-44)."""
    out = run_script(
        "tpu_node_setup.sh", "--role=node", "--yes",
        "--join=kubeadm join 10.0.0.1:6443 --token abc --discovery-token-ca-cert-hash sha256:xyz")
    join = [l for l in out.splitlines() if "kubeadm join" in l and "DRY" in l]
    assert join, out
    assert "--cri-socket=unix:///run/containerd/containerd.sock" in join[0]


def test_node_setup_applies_cni_and_device_plugin_path():
    """Control-plane flow applies the pinned CNI and points at the device
    plugin manifest that actually exists in this repo."""
    out = run_script("tpu_node_setup.sh", "--role=control_plane", "--yes")
    assert "DRY: kubectl apply -f https://raw.githubusercontent.com/projectcalico/calico/v3.28.0/manifests/calico.yaml" in out
    assert "DRY: wait for node Ready" in out
    manifest = "cluster/device-plugin/manifest/daemonset.yaml"
    assert manifest in out
    assert (SCRIPTS.parent.parent / manifest).exists(), (
        "script references a manifest path that does not exist")


def test_node_setup_cni_gate():
    out = run_script("tpu_node_setup.sh", "--role=control_plane", "--yes",
                     env={"APPLY_CNI": "0"})
    assert "skipping CNI" in out
    assert "calico.yaml" not in out.replace("skipping CNI", "")


def test_smoke_check_dry_lists_all_rows():
    """DRY_RUN smoke_check prints every check row from SURVEY §4's table."""
    out = run_script("smoke_check.sh")
    for marker in ("curl --proxy", "systemctl is-active containerd",
                   "sport = :6443", "kubectl get nodes -> all Ready",
                   "google\\.com/tpu", "grep registered",
                   "TPU acceptance pod (google.com/tpu: 1)",
                   "kgct-router-service /health"):
        assert marker in out, f"missing smoke row: {marker}\n{out}"


def test_smoke_check_selects_single_row():
    out = run_script("smoke_check.sh", "runtime")
    assert "systemctl is-active containerd" in out
    assert "TPU acceptance" not in out


def test_runtime_setup_dry():
    out = run_script("runtime_setup.sh")
    assert "DRY" in out


def test_proxy_setup_dry():
    out = run_script("proxy_setup.sh", "--mode=ssh")
    assert "DRY" in out


def test_ha_setup_renders_configs():
    """HA recipe renders the reference's keepalived/haproxy design
    (multi-cp.md:196-291) from flags: one haproxy backend per control plane
    with TLS healthz checks, VRRP instance tracking the apiserver."""
    out = run_script(
        "ha_setup.sh", "--vip=10.0.0.250",
        "--cp-ips=10.0.0.1,10.0.0.2,10.0.0.3", "--interface=ens3",
        "--state=MASTER", "--priority=101",
        env={"AUTH_PASS": "testpass"})
    # haproxy: one server line per CP, healthz check, round robin
    for i, ip in enumerate(["10.0.0.1", "10.0.0.2", "10.0.0.3"], 1):
        assert f"server cp{i} {ip}:6443 check verify none" in out
    assert "http-check send meth GET uri /healthz" in out
    assert "balance roundrobin" in out
    assert "bind *:8443" in out                     # co-located LB port
    # keepalived: VRRP on the right interface/priority, tracked healthz
    assert "interface ens3" in out
    assert "priority 101" in out
    assert "state MASTER" in out
    assert "10.0.0.250" in out
    assert "check_apiserver" in out
    assert "https://localhost:6443/healthz" in out
    # operator handoff: the init one-liner through the VIP
    assert "CONTROL_PLANE_ENDPOINT=10.0.0.250:8443" in out


def test_ha_setup_requires_flags():
    r = subprocess.run(
        ["bash", str(SCRIPTS / "ha_setup.sh")],
        capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "DRY_RUN": "1"})
    assert r.returncode == 1
    assert "--vip" in r.stderr


def test_proxy_setup_xray_mode_dry():
    """Xray VLESS egress provider (reference xray_setup.sh/xray_reset.sh):
    install + config render + hardened unit, all behind DRY_RUN."""
    out = run_script("proxy_setup.sh", "--mode=xray",
                     env={"XRAY_VLESS_URL": "vless://u-u-i-d@vpn.example.com:443"})
    assert "install xray via official install-release.sh" in out
    assert "socks :1080 -> vless outbound" in out
    assert "Restart=always LimitNOFILE=65535" in out
    assert "apt install privoxy" in out          # bridged to :8118


def test_runtime_setup_crun_build_gated():
    """BUILD_CRUN=1 compiles crun from source (reference
    gpu-crio-setup.sh:43-56); off by default."""
    out = run_script("runtime_setup.sh", env={"BUILD_CRUN": "1"})
    assert "git clone --branch 1.21 https://github.com/containers/crun" in out
    out_default = run_script("runtime_setup.sh")
    assert "crun" not in out_default


def test_node_setup_coredns_fix_gated():
    out = run_script("tpu_node_setup.sh", "--role=control_plane", "--yes",
                     env={"FIX_COREDNS": "1"})
    assert "patch configmap coredns" in out
    out_default = run_script("tpu_node_setup.sh", "--role=control_plane",
                             "--yes")
    assert "coredns" not in out_default


def test_proxy_setup_xray_url_parsing():
    """Share-link shaped VLESS URLs (#fragment, tls/ws params) must not
    produce broken or plaintext configs; unsupported types fail loudly."""
    import json
    r = subprocess.run(
        ["bash", str(SCRIPTS / "proxy_setup.sh"),
         "--render-xray-config=vless://uid-1@vpn.example.com:443"
         "?security=tls&type=ws&sni=cdn.example.com&path=/ray#my server"],
        capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "DRY_RUN": "1"})
    assert r.returncode == 0, r.stderr
    cfg = json.loads(r.stdout)
    out = cfg["outbounds"][0]
    assert out["settings"]["vnext"][0]["port"] == 443      # fragment stripped
    ss = out["streamSettings"]
    assert ss["security"] == "tls"
    assert ss["tlsSettings"]["serverName"] == "cdn.example.com"
    assert ss["network"] == "ws"
    assert ss["wsSettings"]["path"] == "/ray"

    r2 = subprocess.run(
        ["bash", str(SCRIPTS / "proxy_setup.sh"),
         "--render-xray-config=vless://uid@h:443?security=reality"],
        capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "DRY_RUN": "1"})
    assert r2.returncode != 0
    assert "unsupported" in r2.stderr
