"""Mixed prefill/decode batching (stall-free TTFT scheduler).

The bar for the mixed path is the same as chunked prefill's: IDENTICAL
output to the legacy prefill-else-decode policy (greedy, and seeded
sampled — per-request seeds derive from (seed, position) so they reproduce
across engines), with decode never stalled behind a prefill window. Plus
the policy/layout contracts: decode rows claim the token budget first, the
unified ragged layout addresses both halves correctly, and the legacy
invariants (mid-chunk sequence only at waiting[0]; preemption never admits
waiting work) survive the mixing path.
"""

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.mixed_batch import (build_mixed_batch,
                                                           plan_chunk_tokens)
from kubernetes_gpu_cluster_tpu.engine.scheduler import Scheduler
from kubernetes_gpu_cluster_tpu.engine.sequence import (Sequence,
                                                        SequenceStatus)


def _cfg(mixed=True, num_pages=65, page_size=4, max_num_seqs=4,
         max_prefill_tokens=16, budget=None, decode_window=2):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages),
        scheduler=SchedulerConfig(
            max_num_seqs=max_num_seqs, max_prefill_tokens=max_prefill_tokens,
            decode_buckets=(1, 2, 4), prefill_buckets=(16, 32, 64),
            decode_window=decode_window, mixed_batch_enabled=mixed,
            decode_priority_token_budget=budget))


def _seq(rid, n_prompt, max_tokens=64):
    return Sequence(rid, list(range(1, n_prompt + 1)),
                    SamplingParams(max_tokens=max_tokens))


class TestPolicy:
    def test_decode_rows_claim_budget_first(self):
        # budget 16, 3 decode rows -> at most 13 chunk tokens
        assert plan_chunk_tokens(100, 3, None, 16) == 13
        assert plan_chunk_tokens(5, 3, None, 16) == 5      # remaining caps
        assert plan_chunk_tokens(100, 16, None, 16) == 0   # no room left
        # explicit mixed budget larger than the prefill budget: the chunk is
        # still capped by max_prefill_tokens
        assert plan_chunk_tokens(100, 4, 64, 16) == 16
        # explicit smaller budget wins
        assert plan_chunk_tokens(100, 1, 8, 16) == 7

    def test_mixed_only_when_decode_and_prefill_coexist(self):
        sched = Scheduler(_cfg(), 65)
        sched.add(_seq("a", 8))
        assert sched.schedule().kind == "prefill"   # nothing running yet
        sched.waiting.append(_seq("b", 40))
        # nothing appended to "a" yet — it still decodes from its prompt
        batch = sched.schedule()
        assert batch.kind == "mixed"

    def test_disabled_keeps_legacy_kinds(self):
        sched = Scheduler(_cfg(mixed=False), 65)
        sched.add(_seq("a", 8))
        assert sched.schedule().kind == "prefill"
        sched.add(_seq("b", 40))
        kinds = {sched.schedule().kind for _ in range(6)}
        assert "mixed" not in kinds

    def test_burst_of_packable_prompts_keeps_legacy_packed_prefill(self):
        """Two+ whole fresh prompts that fit one legacy prefill batch must
        NOT be serialized through head-only mixed steps — one packed step
        admits them all (burst stability); mixing engages once the queue is
        down to a single prompt."""
        sched = Scheduler(_cfg(max_num_seqs=8), 65)
        a = _seq("a", 8)
        sched.add(a)
        assert sched.schedule().kind == "prefill"
        a.append_token(9)
        sched.add(_seq("p1", 6))
        sched.add(_seq("p2", 6))
        sched.add(_seq("p3", 6))
        batch = sched.schedule()
        assert batch.kind == "prefill"         # packed, not mixed
        # budget 16 fits two 6-token prompts per packed step
        assert {s.request_id for s in batch.seqs} == {"p1", "p2"}
        # one fresh prompt left waiting -> stall-free mixing engages
        assert sched.schedule().kind == "mixed"

    def test_chunk_streaming_head_mixes_even_under_burst(self):
        """An oversized head streams through mixed chunks regardless of
        queue depth — long prompts are where prefill stalls hurt most."""
        sched = Scheduler(_cfg(), 65)
        a = _seq("a", 8)
        sched.add(a)
        sched.schedule()
        a.append_token(9)
        sched.add(_seq("long", 40))            # > 16-token budget: chunks
        sched.add(_seq("p1", 6))
        sched.add(_seq("p2", 6))
        assert sched.schedule().kind == "mixed"

    def test_full_occupancy_partial_chunk_stays_in_bucket_grid(self):
        """With every max_num_seqs seat running, D+1 sampled rows would
        escape the decode-bucket grid (next_power_of_2 fallback = an
        unwarmed compile shape mid-serving). Mixing must bow out — even for
        a PARTIAL chunk, which needs no seat — and leave the step to the
        legacy policy."""
        sched = Scheduler(_cfg(max_num_seqs=4), 65)   # buckets (1,2,4)
        seqs = [_seq(f"r{i}", 4) for i in range(4)]
        for s in seqs:
            sched.add(s)
        assert sched.schedule().kind == "prefill"
        for s in seqs:
            s.append_token(9)
        sched.add(_seq("long", 40))                   # chunkable head
        batch = sched.schedule()
        assert batch.kind != "mixed"

    def test_budget_full_of_decodes_falls_back_to_pure_decode(self):
        cfg = _cfg(budget=1)   # 1 decode row already exhausts the budget
        sched = Scheduler(cfg, 65)
        sched.add(_seq("a", 8))
        sched.schedule()
        sched.add(_seq("b", 12))
        batch = sched.schedule()
        # mixing had no room for a chunk; the head won a pure prefill batch
        # (legacy policy) rather than being starved forever
        assert batch.kind == "prefill"


class TestConfigValidation:
    def test_engine_rejects_unusable_mixed_budget(self):
        """A decode-priority budget that can never fit a decode row plus a
        chunk token must fail loudly at engine init, not leave mixing
        silently inert (kgct_mixed_step_ratio reading 0 forever)."""
        with pytest.raises(ValueError, match="decode_priority_token_budget"):
            LLMEngine(_cfg(budget=1))


class TestLayout:
    def _mixed_state(self):
        sched = Scheduler(_cfg(), 65)
        a = _seq("a", 8)
        sched.add(a)
        assert sched.schedule().kind == "prefill"
        a.append_token(9)                      # one decode output committed
        long = _seq("long", 40)
        sched.add(long)
        return sched, a, long

    def test_unified_ragged_layout(self):
        sched, a, long = self._mixed_state()
        batch = sched.schedule()
        assert batch.kind == "mixed"
        assert batch.seqs == [a, long]         # decode rows, then the chunk
        # budget 16 - 1 decode row = 15 chunk tokens
        assert batch.prefill_token_count == 15
        assert batch.partial and batch.hist_len == 0
        assert long.num_prefilled == 15
        Tp = 16                                # _bucket(15, prefill_buckets)
        assert batch.tokens.shape == (Tp + 2,)  # R_pad = _bucket(2, decode)
        np.testing.assert_array_equal(batch.tokens[:15],
                                      long.prompt_token_ids[:15])
        np.testing.assert_array_equal(batch.seg_ids[:15], 0)
        assert batch.seg_ids[15] == -1 and set(batch.seg_ids[Tp:]) == {-1}
        np.testing.assert_array_equal(batch.positions[:15], np.arange(15))
        # decode row: a's last output token at position num_tokens-1
        assert batch.tokens[Tp] == 9
        assert batch.positions[Tp] == a.num_tokens - 1
        assert batch.context_lens[0] == a.num_tokens
        np.testing.assert_array_equal(batch.page_tables[0, :len(a.pages)],
                                      a.pages)
        np.testing.assert_array_equal(
            batch.chunk_page_table[0, :len(long.pages)], long.pages)
        # sampled rows: decode row first, the chunk's last token second
        np.testing.assert_array_equal(batch.logits_indices, [Tp, 14])
        # KV write slots: chunk tokens into long's pages, decode row into a's
        ps = sched.page_size
        pos = a.num_tokens - 1
        assert batch.slot_mapping[Tp] == (a.pages[pos // ps] * ps + pos % ps)
        np.testing.assert_array_equal(
            batch.slot_mapping[:15],
            [long.pages[p // ps] * ps + p % ps for p in range(15)])

    def test_chunk_streams_to_final_and_joins_running(self):
        sched, a, long = self._mixed_state()
        hist = []
        while long.status != SequenceStatus.RUNNING:
            batch = sched.schedule()
            assert batch.kind == "mixed"
            hist.append((batch.hist_len, long.num_prefilled, batch.partial))
        # 40 tokens at 15/step: [0:15) [15:30) [30:40) — final joins running
        assert hist == [(0, 15, True), (15, 30, True), (30, 40, False)]
        assert long in sched.running and long not in sched.waiting
        assert sched.schedule().kind == "decode"   # queue drained


class TestInvariants:
    def test_preempt_victim_slots_behind_mid_chunk_head(self):
        """The legacy invariant — a mid-chunk sequence (holding pages) is
        only ever at waiting[0] — must survive preemption triggered from
        the MIXED path's decode page growth: the victim slots in BEHIND the
        mid-chunk head, never displacing it."""
        cfg = _cfg(num_pages=13, page_size=4, max_num_seqs=4,
                   max_prefill_tokens=16)      # 12 usable pages
        sched = Scheduler(cfg, 13)
        a, b = _seq("a", 8), _seq("b", 8)      # 2 pages each
        sched.add(a)
        sched.add(b)
        assert sched.schedule().kind == "prefill"
        a.append_token(9)
        b.append_token(9)
        long = _seq("long", 40)                # will chunk across many steps
        sched.add(long)
        batch = sched.schedule()               # mixed: chunk takes pages
        assert batch.kind == "mixed" and batch.partial
        assert sched.waiting[0] is long and long.num_prefilled > 0
        assert long.pages                      # mid-chunk head holding pages
        # Exhaust the pool so the next decode growth must preempt: grow a/b
        # to their page boundaries and drain free pages.
        free = sched.allocator.num_free
        if free:
            hold = sched.allocator.allocate(free)
        for s in (a, b):
            while s.num_tokens % 4 != 0:       # fill the current page
                s.append_token(7)
            s.append_token(7)                  # first token of a NEW page
        batch = sched.schedule()
        # b (youngest running) was preempted; the mid-chunk head kept
        # waiting[0] and the victim slotted in at waiting[1].
        assert sched.num_preemptions >= 1
        assert sched.waiting[0] is long
        assert sched.waiting[1] is b
        assert b.status == SequenceStatus.PREEMPTED and not b.pages

    def test_abort_mid_chunk_head_releases_pages(self):
        """Aborting the mid-chunk head (pages held, prompt incomplete)
        under the mixed path frees its pages and unblocks the queue."""
        eng = LLMEngine(_cfg())
        eng.add_request("a", list(range(1, 9)),
                        SamplingParams(max_tokens=8, temperature=0.0))
        eng.step()                             # prefill a
        free0 = eng.scheduler.allocator.num_free
        eng.add_request("long", list(range(1, 61)),
                        SamplingParams(max_tokens=8, temperature=0.0))
        eng.step()                             # mixed: chunk holds pages
        head = eng.scheduler.waiting[0]
        assert head.request_id == "long" and head.num_prefilled > 0
        free_mid = eng.scheduler.allocator.num_free
        held = len(head.pages)
        assert held > 0 and free_mid < free0
        assert eng.abort_request("long")
        # exactly the chunk's pages come back (the survivor's legitimate
        # decode page growth stays)
        assert eng.scheduler.allocator.num_free == free_mid + held
        assert all(s.request_id != "long" for s in eng.scheduler.waiting)
        # engine still serves the survivor to completion
        while eng.has_unfinished_requests():
            outs = eng.step()
        assert not eng.scheduler.has_work()

    def test_mixed_never_preempts_to_admit_prefill(self):
        """Chunk page allocation must never evict running decodes: with no
        free pages for the chunk, mixing bows out and decode proceeds."""
        cfg = _cfg(num_pages=5, page_size=4, max_num_seqs=4)  # 4 usable
        sched = Scheduler(cfg, 5)
        a = _seq("a", 7, max_tokens=1)         # 2 pages (8 slots)
        b = _seq("b", 7, max_tokens=1)         # 2 pages -> pool full
        sched.add(a)
        sched.add(b)
        assert sched.schedule().kind == "prefill"
        a.append_token(9)                      # slot 7: no page growth needed
        b.append_token(9)
        sched.add(_seq("waiting", 8))
        batch = sched.schedule()
        assert batch.kind == "decode"          # no pages for a chunk
        assert sched.num_preemptions == 0
        assert len(batch.seqs) == 2


class TestEngineParity:
    @staticmethod
    def _workload(eng, tag, temperature=0.0, seed=None):
        rng = np.random.default_rng(0)
        prompts = {"a": rng.integers(1, 500, 20).tolist(),
                   "long": rng.integers(1, 500, 70).tolist(),
                   "b": rng.integers(1, 500, 12).tolist()}
        params = SamplingParams(max_tokens=8, temperature=temperature,
                                top_k=40 if temperature else 0, seed=seed)
        outs, kinds = {}, []
        eng.add_request(f"{tag}-a", prompts["a"], params)
        for _ in range(2):                      # a prefills, starts decoding
            for o in eng.step():
                if o.finished:
                    outs[o.request_id] = o.output_token_ids
        eng.add_request(f"{tag}-long", prompts["long"], params)
        eng.add_request(f"{tag}-b", prompts["b"], params)
        while eng.has_unfinished_requests():
            for o in eng.step():
                if o.finished:
                    outs[o.request_id] = o.output_token_ids
            if eng._last_step_info:
                kinds.append(eng._last_step_info[0])
        return {k.split("-", 1)[1]: v for k, v in outs.items()}, kinds

    def test_outputs_identical_to_legacy(self):
        """Greedy AND seeded-sampled outputs must be byte-identical to the
        legacy policy (per-request seeds derive from (seed, position), so
        they reproduce across engines). One engine pair serves both
        workloads — mid-decode arrivals exercise the mixed path, whose
        steps the legacy engine must never take."""
        legacy = LLMEngine(_cfg(mixed=False, max_prefill_tokens=32))
        mixed = LLMEngine(_cfg(mixed=True, max_prefill_tokens=32))
        ref, kinds_off = self._workload(legacy, "g")
        got, kinds_on = self._workload(mixed, "g")
        assert "mixed" in kinds_on and "mixed" not in kinds_off
        assert got == ref
        # the long prompt streamed through mixed steps instead of stalling
        # the running decodes behind pure prefill windows
        assert mixed.obs.mixed_prefill_tokens > 0
        assert mixed.obs.mixed_decode_tokens > 0
        # seeded sampled workload on the same engines
        ref, _ = self._workload(legacy, "s", temperature=1.0, seed=7)
        got, kinds = self._workload(mixed, "s", temperature=1.0, seed=7)
        assert "mixed" in kinds
        assert got == ref
        # observability rode along: ratio gauge, token counters, and
        # per-step trace events with the prefill/decode split
        ratio = mixed.obs.mixed_step_ratio()
        assert ratio is not None and 0.0 < ratio < 1.0
        assert mixed.obs.step_kind_counts["mixed"] >= 1
        assert legacy.obs.mixed_step_ratio() == 0.0
        text = "\n".join(mixed.obs.render_prometheus())
        assert "kgct_mixed_step_ratio" in text
        assert "kgct_mixed_prefill_tokens_total" in text
        assert "kgct_mixed_decode_tokens_total" in text
        mixed_events = [e for e in mixed.obs.tracer.events()
                        if e.kind == "mixed"]
        assert mixed_events
        assert all(e.args["prefill_tokens"] > 0
                   and e.args["decode_tokens"] > 0 for e in mixed_events)


class TestObservability:
    def test_fresh_engine_ratio_is_none_and_renders_clean(self):
        from kubernetes_gpu_cluster_tpu.observability import Observability
        obs = Observability(enabled=True)
        assert obs.mixed_step_ratio() is None
        text = "\n".join(obs.render_prometheus())
        assert "nan" not in text.lower()
        # gauge absent (None renders nothing); counters present at 0
        assert "kgct_mixed_step_ratio " not in text
        assert "kgct_mixed_prefill_tokens_total 0" in text
