"""Weight loading: HF safetensors -> stacked params, verified against the HF
(torch CPU) forward pass on locally generated tiny checkpoints — the
zero-egress analogue of "bench runs TinyLlama with real weights and matches
HF logits" (no downloads possible in CI; architecture coverage is identical).
"""

import json

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")

import jax

from kubernetes_gpu_cluster_tpu.engine.weights import (
    config_from_hf, load_weights, resolve_model)
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.models.registry import resolve


def _hf_llama_dir(tmp_path, tie=False, qwen2=False):
    from transformers import LlamaConfig, LlamaForCausalLM
    from transformers import Qwen2Config, Qwen2ForCausalLM

    kw = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=256,
              rope_theta=10000.0, rms_norm_eps=1e-5,
              tie_word_embeddings=tie)
    torch.manual_seed(0)
    if qwen2:
        model = Qwen2ForCausalLM(Qwen2Config(**kw))
    else:
        model = LlamaForCausalLM(LlamaConfig(**kw, attention_bias=False))
    model.eval()
    d = tmp_path / ("qwen2" if qwen2 else f"llama{'-tied' if tie else ''}")
    model.save_pretrained(d, safe_serialization=True)
    return model, str(d)


def _our_logits(path, prompt):
    cfg = config_from_hf(path).replace(dtype="float32")
    params = load_weights(path, cfg)
    T = len(prompt)
    meta = model_lib.PrefillMeta(
        seg_ids=jnp.zeros((T,), jnp.int32),
        positions=jnp.arange(T, dtype=jnp.int32),
        slot_mapping=jnp.arange(T, dtype=jnp.int32),  # scratch pool below
        logits_indices=jnp.asarray([T - 1], jnp.int32))
    from kubernetes_gpu_cluster_tpu.config import CacheConfig
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
    kv = allocate_kv_cache(cfg, CacheConfig(page_size=16, num_pages=4), 4)
    _, _, h = model_lib.forward_prefill(params, cfg, jnp.asarray(prompt), meta,
                                        kv, use_pallas=False)
    h = model_lib._norm(cfg, h, params, "final_norm")
    return np.asarray(model_lib.compute_logits(params, cfg, h))   # [T, V]


class TestHFParity:
    @pytest.mark.parametrize("tie", [False, True])
    def test_llama_logits_match(self, tmp_path, tie):
        model, path = _hf_llama_dir(tmp_path, tie=tie)
        prompt = [1, 17, 99, 4, 63, 2, 118, 30]
        with torch.no_grad():
            ref = model(torch.tensor([prompt])).logits[0].numpy()
        got = _our_logits(path, prompt)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_qwen2_logits_match(self, tmp_path):
        model, path = _hf_llama_dir(tmp_path, qwen2=True)
        prompt = [3, 8, 110, 5]
        with torch.no_grad():
            ref = model(torch.tensor([prompt])).logits[0].numpy()
        got = _our_logits(path, prompt)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def _hf_opt_dir(tmp_path):
    from transformers import OPTConfig, OPTForCausalLM
    cfg = OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=256,
        do_layer_norm_before=True, activation_function="relu")
    torch.manual_seed(3)
    model = OPTForCausalLM(cfg).eval()
    d = tmp_path / "opt"
    model.save_pretrained(d, safe_serialization=True)
    return model, str(d)


class TestOPTParity:
    """The reference's minimal-example model family (facebook/opt-125m,
    reference values-01-minimal-example.yaml:4-8), served through the shared
    decoder graph via config flags (learned positions, pre-LN LayerNorm,
    biased ReLU MLP, tied head)."""

    def test_opt_logits_match_hf(self, tmp_path):
        model, path = _hf_opt_dir(tmp_path)
        prompt = [2, 17, 99, 4, 63, 30]
        with torch.no_grad():
            ref = model(torch.tensor([prompt])).logits[0].numpy()
        got = _our_logits(path, prompt)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_opt_config_fields(self, tmp_path):
        _, path = _hf_opt_dir(tmp_path)
        cfg = config_from_hf(path)
        assert cfg.norm_type == "layernorm"
        assert cfg.pos_embedding == "learned"
        assert cfg.mlp_type == "mlp" and cfg.mlp_act == "relu"
        assert cfg.linear_bias and cfg.attention_bias
        assert cfg.tie_word_embeddings
        assert (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim) == (4, 4, 16)

    def test_opt_engine_greedy_matches_hf(self, tmp_path):
        model, path = _hf_opt_dir(tmp_path)
        from kubernetes_gpu_cluster_tpu.config import (
            CacheConfig, EngineConfig, SchedulerConfig)
        from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

        cfg = config_from_hf(path).replace(dtype="float32")
        params = load_weights(path, cfg)
        eng = LLMEngine(
            EngineConfig(model=cfg,
                         cache=CacheConfig(page_size=16, num_pages=64),
                         scheduler=SchedulerConfig(
                             max_num_seqs=2, max_prefill_tokens=64,
                             decode_buckets=(1, 2), prefill_buckets=(32, 64),
                             decode_window=2)),
            params=params)
        prompt = [2, 5, 9, 33]
        out = eng.generate([prompt], SamplingParams(max_tokens=6,
                                                    temperature=0.0))[0]
        with torch.no_grad():
            ids = torch.tensor([prompt])
            hf_tokens = []
            for _ in range(6):
                nxt = model(ids).logits[0, -1].argmax().item()
                hf_tokens.append(nxt)
                ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
        assert out.output_token_ids == hf_tokens

    def test_opt_preset_resolves(self):
        from kubernetes_gpu_cluster_tpu.config import get_model_config
        cfg = get_model_config("facebook/opt-125m")
        assert cfg.name == "opt-125m" and cfg.pos_embedding == "learned"

    def test_opt_tp_sharded_load_matches(self, tmp_path):
        """OPT under a tp=2 mesh: sharded placement + GSPMD serving parity."""
        import jax
        from kubernetes_gpu_cluster_tpu.engine.engine import resolve_shardings
        from kubernetes_gpu_cluster_tpu.parallel import make_mesh

        model, path = _hf_opt_dir(tmp_path)
        cfg = config_from_hf(path).replace(dtype="float32")
        full = load_weights(path, cfg)
        mesh = make_mesh(tp=2)
        shardings, _ = resolve_shardings(mesh, cfg)
        sharded = load_weights(path, cfg, shardings=shardings)
        for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(sharded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestConfigFromHF:
    def test_fields(self, tmp_path):
        _, path = _hf_llama_dir(tmp_path)
        cfg = config_from_hf(path)
        assert (cfg.vocab_size, cfg.hidden_size, cfg.num_layers) == (128, 64, 2)
        assert (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim) == (4, 2, 16)
        assert not cfg.attention_bias and not cfg.qk_norm and not cfg.is_moe

    def test_resolve_local_dir_vs_preset(self, tmp_path):
        _, path = _hf_llama_dir(tmp_path)
        cfg, weights, tok = resolve_model(path)
        assert weights == path and tok == path
        r = resolve("tinyllama-1.1b")
        assert r.weights_path is None and r.config.name == "tinyllama-1.1b"


class TestEngineWithRealWeights:
    def test_generate_with_loaded_weights(self, tmp_path):
        """End-to-end: engine serves a loaded checkpoint, greedy tokens match
        HF greedy continuation."""
        model, path = _hf_llama_dir(tmp_path)
        from kubernetes_gpu_cluster_tpu.config import (
            CacheConfig, EngineConfig, SchedulerConfig)
        from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

        cfg = config_from_hf(path).replace(dtype="float32")
        params = load_weights(path, cfg)
        eng = LLMEngine(
            EngineConfig(model=cfg,
                         cache=CacheConfig(page_size=16, num_pages=64),
                         scheduler=SchedulerConfig(
                             max_num_seqs=2, max_prefill_tokens=64,
                             decode_buckets=(1, 2), prefill_buckets=(32, 64),
                             decode_window=2)),
            params=params)
        prompt = [1, 5, 9, 33]
        out = eng.generate([prompt], SamplingParams(max_tokens=6,
                                                    temperature=0.0))[0]
        with torch.no_grad():
            ids = torch.tensor([prompt])
            hf_tokens = []
            for _ in range(6):
                nxt = model(ids).logits[0, -1].argmax().item()
                hf_tokens.append(nxt)
                ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
        assert out.output_token_ids == hf_tokens


class TestRopeScaling:
    def test_llama3_rope_scaling_parsed_and_applied(self, tmp_path):
        import json
        import numpy as np
        from kubernetes_gpu_cluster_tpu.engine.weights import config_from_hf
        from kubernetes_gpu_cluster_tpu.ops.rope import scaled_inv_freq
        hf = {"architectures": ["LlamaForCausalLM"], "vocab_size": 128,
              "hidden_size": 64, "intermediate_size": 128,
              "num_hidden_layers": 2, "num_attention_heads": 4,
              "num_key_value_heads": 2, "rope_theta": 500000.0,
              "rope_scaling": {"rope_type": "llama3", "factor": 8.0,
                               "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                               "original_max_position_embeddings": 8192}}
        (tmp_path / "config.json").write_text(json.dumps(hf))
        cfg = config_from_hf(str(tmp_path))
        scaling = cfg.rope_scaling_dict
        assert scaling["rope_type"] == "llama3"
        scaled = scaled_inv_freq(cfg.head_dim, cfg.rope_theta, scaling)
        plain = scaled_inv_freq(cfg.head_dim, cfg.rope_theta, None)
        # high-frequency components untouched; lowest stretched by ~factor
        assert np.isclose(scaled[0], plain[0])
        assert np.isclose(scaled[-1], plain[-1] / 8.0, rtol=0.2)

    def test_unsupported_rope_scaling_rejected(self, tmp_path):
        import json
        import pytest
        from kubernetes_gpu_cluster_tpu.engine.weights import config_from_hf
        hf = {"architectures": ["LlamaForCausalLM"], "vocab_size": 128,
              "hidden_size": 64, "intermediate_size": 128,
              "num_hidden_layers": 2, "num_attention_heads": 4,
              "rope_scaling": {"rope_type": "yarn", "factor": 4.0}}
        (tmp_path / "config.json").write_text(json.dumps(hf))
        with pytest.raises(ValueError, match="yarn"):
            config_from_hf(str(tmp_path))
