"""Disaggregated prefill/decode serving: the KV export/import seam, the
handoff wire codec, and the role-split serving topology.

Tier-1 keeps the CHEAP pins: one shared debug-tiny engine proves the
acceptance contract — a disaggregated run (prefill-with-hold -> export ->
wire round-trip -> import -> decode resume) is BYTE-IDENTICAL to a
colocated run for greedy and seeded-sampled decoding — plus engine-free
codec/fetch pins. The multi-engine HTTP topology (role-split replicas
behind the real router) and the bench phase are @slow, per the tier-1
budget guard.
"""

import asyncio
import dataclasses
import time

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.handoff import (
    decode_handoff, encode_handoff, handoff_request_body)


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


def _engine_config(**sched_kw):
    kw = dict(max_num_seqs=4, max_prefill_tokens=64,
              decode_buckets=(1, 2), prefill_buckets=(64,),
              decode_window=4, mixed_batch_enabled=False)
    kw.update(sched_kw)
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(**kw))


@pytest.fixture(scope="module")
def engine():
    """ONE debug-tiny engine serves as colocated reference, prefill
    replica, AND decode replica (identical weights by construction; the
    handoff still crosses the full gather -> host buffer -> wire -> scatter
    path, which is exactly what distinct replicas exchange)."""
    return LLMEngine(_engine_config())


PROMPT = np.random.default_rng(3).integers(1, 500, 40).tolist()


def _run_to_completion(eng, rid):
    out_tokens = None
    while eng.has_unfinished_requests():
        for o in eng.step():
            if o.request_id == rid and o.finished:
                out_tokens = list(o.output_token_ids)
    return out_tokens


def _disagg_roundtrip(eng, rid, prompt, params):
    """prefill(hold, max_tokens=1) -> export -> WIRE round-trip -> import
    -> decode to completion. Returns the final output token ids."""
    eng.add_request(f"{rid}-pf", prompt,
                    dataclasses.replace(params, max_tokens=1), hold_kv=True)
    while eng.has_unfinished_requests():
        eng.step()
    state = eng.export_held(f"{rid}-pf")
    state = decode_handoff(encode_handoff(state))   # the actual wire bytes
    outs = eng.import_request(f"{rid}-dc", prompt, params, state)
    assert outs[0].new_token_ids == state["output_token_ids"]
    if outs[0].finished:
        return list(outs[0].output_token_ids)
    return _run_to_completion(eng, f"{rid}-dc")


class TestHandoffByteIdentity:
    def test_greedy_identical_to_colocated(self, engine):
        params = SamplingParams(max_tokens=12, temperature=0.0)
        ref = engine.generate([PROMPT], params)[0].output_token_ids
        got = _disagg_roundtrip(engine, "g", PROMPT, params)
        assert got == ref

    def test_seeded_sampled_identical_to_colocated(self, engine):
        params = SamplingParams(max_tokens=12, temperature=0.8,
                                top_k=30, top_p=0.95, seed=17)
        ref = engine.generate([PROMPT], params)[0].output_token_ids
        got = _disagg_roundtrip(engine, "s", PROMPT, params)
        assert got == ref

    def test_no_pages_leak_across_the_handoff(self, engine):
        alloc = engine.scheduler.allocator
        free0 = alloc.num_free
        params = SamplingParams(max_tokens=4, temperature=0.0)
        _disagg_roundtrip(engine, "leak", PROMPT, params)
        assert alloc.num_free == free0

    def test_eos_on_first_token_finishes_at_import(self, engine):
        """A prompt whose first sampled token is a stop token finishes the
        imported sequence immediately — no decode step, pages released."""
        params = SamplingParams(max_tokens=8, temperature=0.0)
        ref = engine.generate([PROMPT], params)[0]
        stop_tok = ref.output_token_ids[0]
        params = SamplingParams(max_tokens=8, temperature=0.0,
                                stop_token_ids=(stop_tok,))
        free0 = engine.scheduler.allocator.num_free
        got = _disagg_roundtrip(engine, "eos", PROMPT, params)
        assert got == [stop_tok]
        assert engine.scheduler.allocator.num_free == free0

    def test_discard_held_releases_without_export(self, engine):
        free0 = engine.scheduler.allocator.num_free
        engine.add_request(
            "dis-pf", PROMPT, SamplingParams(max_tokens=1, temperature=0.0),
            hold_kv=True)
        while engine.has_unfinished_requests():
            engine.step()
        assert "dis-pf" in engine.scheduler.held
        engine.discard_held("dis-pf")
        engine.discard_held("dis-pf")   # idempotent
        assert engine.scheduler.allocator.num_free == free0
        with pytest.raises(KeyError):
            engine.export_held("dis-pf")

    def test_abort_releases_held_kv(self, engine):
        """abort_request must scan ``held`` too: a kv_handoff handler
        cancelled between the prefill finishing and the export consuming
        it aborts the request — without this the held pages leak until
        the prefill replica is capacity-dead."""
        free0 = engine.scheduler.allocator.num_free
        engine.add_request(
            "abt-pf", PROMPT, SamplingParams(max_tokens=1, temperature=0.0),
            hold_kv=True)
        while engine.has_unfinished_requests():
            engine.step()
        assert "abt-pf" in engine.scheduler.held
        engine.abort_request("abt-pf")
        assert "abt-pf" not in engine.scheduler.held
        assert engine.scheduler.allocator.num_free == free0

    def test_import_records_decode_side_ttft(self, engine):
        """step() never fires on_first_token for an imported sequence
        (append_token stamps first_token_time at import), so the decode
        side's TTFT sample — remote prefill + transfer + import, measured
        from the serving layer's ``_ttft_t0`` stamp — lands in
        import_request: SLO attainment window AND the goodput gate must
        judge the real span, not the ~0 of first_token - arrival."""
        obs = engine.obs
        params = SamplingParams(max_tokens=4, temperature=0.0)
        engine.add_request("ttft-pf", PROMPT,
                           dataclasses.replace(params, max_tokens=1),
                           hold_kv=True)
        while engine.has_unfinished_requests():
            engine.step()
        state = engine.export_held("ttft-pf")
        obs.slo.clear()
        state["_ttft_t0"] = time.monotonic() - 5.0   # the pull "took" 5 s
        engine.import_request("ttft-dc", PROMPT, params, state)
        ttfts = list(obs.slo._ttfts)
        assert len(ttfts) == 1 and ttfts[0] >= 5.0
        # 5 s against the 1 s default budget: a pure-handoff decode
        # replica must NOT read a pegged-1.0 attainment.
        assert obs.slo.attainment() == 0.0
        _run_to_completion(engine, "ttft-dc")
        # ...and the finish-side goodput gate judged the same 5 s (over
        # budget -> the tokens are not goodput).
        assert len(obs.slo._good) == 0
        obs.slo.clear()

    def test_malformed_output_state_rejected_without_page_leak(self, engine):
        """A peer whose frame passes the shape/dtype/prompt checks but
        carries garbage OUTPUT state (non-int tokens, non-pair
        top-logprobs) must be rejected BEFORE any pages are allocated —
        the conversion used to run post-scatter, so every such handoff
        leaked the imported pages while the broad serving-layer fallback
        swallowed the error."""
        params = SamplingParams(max_tokens=4, temperature=0.0)
        engine.add_request("mal-pf", PROMPT,
                           dataclasses.replace(params, max_tokens=1),
                           hold_kv=True)
        while engine.has_unfinished_requests():
            engine.step()
        state = engine.export_held("mal-pf")
        free0 = engine.scheduler.allocator.num_free
        for field, garbage in (("output_token_ids", ["x"]),
                               ("output_logprobs", ["nope"]),
                               ("output_top_logprobs", [5])):
            bad = dict(state, **{field: garbage})
            with pytest.raises(ValueError, match="malformed handoff"):
                engine.import_request(f"mal-{field}", PROMPT, params, bad)
            assert engine.scheduler.allocator.num_free == free0
        # The untouched state still imports (and is drained clean).
        outs = engine.import_request("mal-ok", PROMPT, params, state)
        assert outs[0].new_token_ids
        _run_to_completion(engine, "mal-ok")

    def test_failed_pull_backdates_arrival(self, engine):
        """A decode replica whose handoff pull FAILED admits the request
        only after the pull burned its wall time (up to the handoff
        timeout). add_request(arrival_t0=) backdates the arrival stamp so
        the client-observed wait reaches the TTFT histogram and the SLO
        attainment window instead of reading a green post-pull arrival."""
        obs = engine.obs
        obs.slo.clear()
        t0 = time.monotonic() - 5.0
        engine.add_request("bkd", PROMPT,
                           SamplingParams(max_tokens=2, temperature=0.0),
                           arrival_t0=t0)
        seq = next(s for s in engine.scheduler.waiting
                   if s.request_id == "bkd")
        assert seq.arrival_time == t0
        _run_to_completion(engine, "bkd")
        ttfts = list(obs.slo._ttfts)
        assert len(ttfts) == 1 and ttfts[0] >= 5.0
        assert obs.slo.attainment() == 0.0
        obs.slo.clear()

    def test_import_rejects_mismatched_state(self, engine):
        params = SamplingParams(max_tokens=4, temperature=0.0)
        engine.add_request("rej-pf", PROMPT,
                           dataclasses.replace(params, max_tokens=1),
                           hold_kv=True)
        while engine.has_unfinished_requests():
            engine.step()
        state = engine.export_held("rej-pf")
        with pytest.raises(ValueError, match="prompt does not match"):
            engine.import_request("rej-a", PROMPT[:-1] + [1], params, state)
        bad = dict(state, page_size=state["page_size"] * 2)
        with pytest.raises(ValueError, match="page_size"):
            engine.import_request("rej-b", PROMPT, params, bad)
        bad = dict(state, model="llama-3-8b")
        with pytest.raises(ValueError, match="model"):
            engine.import_request("rej-c", PROMPT, params, bad)
        # The well-formed state still imports (and is drained clean).
        outs = engine.import_request("rej-d", PROMPT, params, state)
        assert outs[0].new_token_ids
        _run_to_completion(engine, "rej-d")


class TestHandoffWireCodec:
    """Engine-free pins of the binary frame (serving/handoff.py)."""

    def _state(self, dtype="float32"):
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, 3, 16, 64)).astype(dtype)
        return {"model": "debug-tiny", "page_size": 16, "dtype": dtype,
                "prompt_token_ids": [1, 2, 3], "output_token_ids": [7],
                "output_logprobs": [-0.5], "output_top_logprobs": [],
                "k": k, "v": k + 1}

    def test_roundtrip(self):
        state = self._state()
        out = decode_handoff(encode_handoff(state))
        assert out["prompt_token_ids"] == [1, 2, 3]
        assert out["output_token_ids"] == [7]
        np.testing.assert_array_equal(out["k"], state["k"])
        np.testing.assert_array_equal(out["v"], state["v"])

    def test_bfloat16_roundtrip(self):
        """TPU pools are bf16: tobytes/frombuffer must round-trip the
        ml_dtypes family without pickle."""
        import ml_dtypes
        state = self._state()
        state["k"] = state["k"].astype(ml_dtypes.bfloat16)
        state["v"] = state["v"].astype(ml_dtypes.bfloat16)
        state["dtype"] = "bfloat16"
        out = decode_handoff(encode_handoff(state))
        assert out["k"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(out["k"], state["k"])

    def test_corrupt_frames_rejected(self):
        state = self._state()
        data = encode_handoff(state)
        with pytest.raises(ValueError, match="magic"):
            decode_handoff(b"NOTAKV" + data[6:])
        with pytest.raises(ValueError, match="!= 2 x"):
            decode_handoff(data[:-7])          # truncated payload
        with pytest.raises(ValueError):
            decode_handoff(data[:10])          # truncated header

    def test_request_body_forwards_sampling_and_tenant_fields_only(self):
        """Forwarded: the sampling fields that shape the first token plus
        the QoS tenant keys (user/session_id — the prefill replica
        resolves the request's tier from them, since the pull carries no
        client headers). Never forwarded: text prompt (the prefill side
        must not re-tokenize), stream, max_tokens (clamped to 1 by the
        handoff handler)."""
        body = {"prompt": "ignored", "temperature": 0.5, "seed": 3,
                "stream": True, "max_tokens": 99, "user": "u"}
        fwd = handoff_request_body([1, 2], body)
        assert fwd == {"prompt_token_ids": [1, 2], "temperature": 0.5,
                       "seed": 3, "user": "u"}


class TestBoundedFetch:
    """The decode side's pull is bounded in bytes and never trusts an
    oversized response (engine-free aiohttp stub)."""

    def test_oversized_blob_rejected(self):
        from aiohttp import web as aioweb

        import aiohttp
        from kubernetes_gpu_cluster_tpu.serving.handoff import fetch_handoff

        async def scenario():
            async def kv(request):
                return aioweb.Response(body=b"x" * 4096)

            app = aioweb.Application()
            app.router.add_post("/internal/kv_handoff", kv)
            runner = aioweb.AppRunner(app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            url = f"http://127.0.0.1:{runner.addresses[0][1]}"
            try:
                async with aiohttp.ClientSession() as sess:
                    with pytest.raises(RuntimeError, match="bound"):
                        await fetch_handoff(sess, url, {}, "rid",
                                            max_bytes=1024, timeout_s=5)
                    data = await fetch_handoff(sess, url, {}, "rid",
                                               max_bytes=8192, timeout_s=5)
                    assert len(data) == 4096
                    # Non-200 raises with a bounded error peek.
                    with pytest.raises(RuntimeError, match="404"):
                        await fetch_handoff(sess, url + "/nope", {}, "rid",
                                            max_bytes=8192, timeout_s=5)
            finally:
                await runner.cleanup()
        asyncio.run(scenario())


# ---------------------------------------------------------------------------
# Role-split serving topology over real sockets (multi-engine: @slow)
# ---------------------------------------------------------------------------

def _serve(role, runners):
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server

    async def start():
        srv = build_server(_engine_config(), None, "debug-tiny", role=role)
        runner = aioweb.AppRunner(srv.build_app())
        await runner.setup()
        site = aioweb.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runners.append(runner)
        return srv, f"http://127.0.0.1:{runner.addresses[0][1]}"
    return start()


@pytest.mark.slow
class TestDisaggServing:
    def test_role_split_pools_byte_identical_to_colocated(self):
        """The acceptance topology end-to-end: 1 prefill + 1 decode
        replica behind the real router (distinct engines, identical
        seeds) produce the same greedy AND seeded-sampled completions as
        a single role="both" replica, with handoff metrics/trace evidence
        on both sides."""
        import aiohttp
        from aiohttp import web as aioweb

        from kubernetes_gpu_cluster_tpu.serving.router import Router

        async def scenario():
            runners = []
            prompt = np.random.default_rng(5).integers(1, 200, 40).tolist()
            greedy = {"prompt": prompt, "max_tokens": 8, "temperature": 0.0}
            seeded = {"prompt": prompt, "max_tokens": 8, "temperature": 0.9,
                      "top_k": 30, "seed": 11}
            try:
                _, u0 = await _serve("both", runners)
                async with aiohttp.ClientSession() as sess:
                    async def text_of(base, body):
                        async with sess.post(f"{base}/v1/completions",
                                             json=body) as resp:
                            assert resp.status == 200, await resp.text()
                            return (await resp.json())["choices"][0]["text"]

                    ref_g = await text_of(u0, greedy)
                    ref_s = await text_of(u0, seeded)

                    pf_srv, pf_url = await _serve("prefill", runners)
                    dc_srv, dc_url = await _serve("decode", runners)
                    router = Router([dc_url], health_interval_s=9999,
                                    prefill_urls=[pf_url])
                    rrunner = aioweb.AppRunner(router.build_app())
                    await rrunner.setup()
                    rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
                    await rsite.start()
                    runners.append(rrunner)
                    ru = f"http://127.0.0.1:{rrunner.addresses[0][1]}"

                    assert await text_of(ru, greedy) == ref_g
                    assert await text_of(ru, seeded) == ref_s

                    async with sess.get(f"{dc_url}/metrics") as resp:
                        dc_text = await resp.text()
                    async with sess.get(f"{pf_url}/metrics") as resp:
                        pf_text = await resp.text()
                    assert ('kgct_disagg_handoffs_total{side="import",'
                            'outcome="ok"} 2') in dc_text
                    assert ('kgct_disagg_handoffs_total{side="export",'
                            'outcome="ok"} 2') in pf_text
                    assert 'kgct_engine_role{role="decode"} 1' in dc_text
                    assert 'kgct_engine_role{role="prefill"} 1' in pf_text
                    # Handoff spans on both sides of the seam.
                    dc_kinds = [e["kind"] for e in
                                dc_srv.engine.engine.obs.flight.export()
                                ["events"]]
                    pf_kinds = [e["kind"] for e in
                                pf_srv.engine.engine.obs.flight.export()
                                ["events"]]
                    assert "handoff" in dc_kinds
                    assert "handoff" in pf_kinds
            finally:
                for runner in reversed(runners):
                    await runner.cleanup()
        asyncio.run(scenario())

    def test_bench_disagg_phase_structure(self):
        """The KGCT_BENCH_DISAGG A/B end-to-end: both arms report TPOT
        p95/TTFT p50 from one router scrape, handoffs really happened, and
        the ratio headline is present. On one CPU core both arms serialize
        on the same device, so the honest expectation is PARITY (~1.03
        measured with fair warmup) — the ratio bound below only guards
        against a regression that makes the handoff path itself slow the
        decode pool down; the separation the A/B exists to show needs
        parallel devices (ROADMAP TPU capture)."""
        import bench

        out = bench._measure_disagg()
        assert out["disagg"]["handoffs_ok"] > 0
        for arm in ("colocated", "disagg"):
            assert out[arm]["decode_tpot_p95_ms"] is not None
            assert out[arm]["ttft_p50_ms"] is not None
        assert out["tpot_p95_ratio"] is not None
        # Parity within single-core scheduling noise.
        assert out["tpot_p95_ratio"] <= 1.25
