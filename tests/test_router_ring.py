"""Cache-aware fleet routing: hash-ring properties, bounded-load pick
policy, session stickiness, and membership-churn remap — all pure-Python /
aiohttp simulation, no engines (the multi-engine router bench phase is the
one @slow test at the bottom).

Correctness here is a DISTRIBUTION property: the ring must be
deterministic across router instances (hashlib, never the salted builtin
``hash``), stable under membership churn (only the dead replica's ~K/N
keys remap), and the least-inflight default must stay byte-identical to
the pre-affinity router so existing deployments see zero behavior change.
"""

import asyncio
from collections import Counter

import pytest

from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.router import HashRing, Router
from test_serving import _assert_valid_exposition


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


URLS = [f"http://replica-{i}:8000" for i in range(4)]
KEYS = [f"session-{i}".encode() for i in range(400)]


class TestHashRing:
    def test_deterministic_across_instances(self):
        """Two rings from identical configs agree on every key — the
        process-restart / multi-router-replica contract (builtin ``hash``
        is salted per process and would break this silently)."""
        a, b = HashRing(URLS), HashRing(URLS)
        assert [a.owner(k) for k in KEYS] == [b.owner(k) for k in KEYS]

    def test_vnode_balance_within_bound(self):
        """Raw key-space shares stay within ~1.6x fair at RING_VNODES=64
        (the CHWBL load bound does the rest at pick time)."""
        counts = Counter(HashRing(URLS).owner(k) for k in KEYS)
        assert set(counts) == set(URLS), "some replica owns no keys"
        fair = len(KEYS) / len(URLS)
        assert max(counts.values()) <= 1.6 * fair, counts

    def test_single_removal_remaps_at_most_2k_over_n(self):
        """Consistent-hashing contract: removing 1 of N replicas moves
        ONLY that replica's keys (<= ~K/N, pinned at 2K/N); every other
        key keeps its owner."""
        full = HashRing(URLS)
        shrunk = HashRing(URLS[:-1])
        moved = sum(1 for k in KEYS if full.owner(k) != shrunk.owner(k))
        assert moved <= 2 * len(KEYS) / len(URLS), moved
        survivors_moved = [
            k for k in KEYS
            if full.owner(k) != URLS[-1] and full.owner(k) != shrunk.owner(k)]
        assert survivors_moved == []

    def test_walk_skip_equals_membership_removal(self):
        """Skipping a dead URL while walking the full ring lands exactly
        where a ring built without it would — health churn never needs a
        ring rebuild."""
        full = HashRing(URLS)
        shrunk = HashRing(URLS[:-1])
        for k in KEYS[:100]:
            walked = next(u for u in full.walk(k) if u != URLS[-1])
            assert walked == shrunk.owner(k)

    def test_walk_yields_every_member_once(self):
        walk = list(HashRing(URLS).walk(b"any-key"))
        assert sorted(walk) == sorted(URLS)
        assert walk[0] == HashRing(URLS).owner(b"any-key")


def _router(policy="prefix-affinity", urls=URLS, **kw):
    # Never started: _pick / _affinity_key are pure and need no session.
    return Router(list(urls), routing_policy=policy, **kw)


class TestPickPolicy:
    def test_identical_configs_identical_assignments(self):
        """Acceptance pin: two router instances with the same config route
        K sampled keys identically."""
        r1, r2 = _router(), _router()
        assert ([r1._pick(affinity_key=k).url for k in KEYS]
                == [r2._pick(affinity_key=k).url for k in KEYS])

    def test_session_stickiness(self):
        router = _router()
        first = router._pick(affinity_key=b"sticky")
        for _ in range(5):
            assert router._pick(affinity_key=b"sticky") is first
        assert router.affinity_hits_total == 6
        assert router.affinity_requests_total == 6

    def test_bounded_load_overflow_walks_to_ring_successor(self):
        """An over-bound owner spills to the NEXT under-bound replica in
        ring order (deterministic — not least-inflight scatter), and the
        overflow is charged to the owner's counter."""
        router = _router(balance_factor=1.0)
        key = b"hot-prefix"
        owner_url = router.ring.owner(key)
        owner = next(r for r in router.replicas if r.url == owner_url)
        owner.inflight = 8      # others idle: bound = ceil(9/4) = 3
        picked = router._pick(affinity_key=key)
        successor = next(u for u in router.ring.walk(key)
                         if u != owner_url)
        assert picked.url == successor
        assert router.affinity_overflow_total[owner_url] == 1
        assert router.affinity_hits_total == 0
        # Owner drains below bound: the key comes home.
        owner.inflight = 0
        assert router._pick(affinity_key=key).url == owner_url

    def test_unhealthy_owner_remaps_and_recovers(self):
        router = _router()
        key = b"some-session"
        owner_url = router.ring.owner(key)
        owner = next(r for r in router.replicas if r.url == owner_url)
        owner.healthy = False
        picked = router._pick(affinity_key=key)
        assert picked.url == next(u for u in router.ring.walk(key)
                                  if u != owner_url)
        assert router.ring_remaps_total == 1
        owner.healthy = True
        assert router._pick(affinity_key=key).url == owner_url

    def test_retry_exclude_flows_through_pick_seam(self):
        """The connect-failure retry path (exclude=tried) remaps the SAME
        affinity key deterministically to the ring successor — same seam,
        same walk."""
        router = _router()
        key = b"retry-me"
        first = router._pick(affinity_key=key)
        second = router._pick(affinity_key=key, exclude={first.url})
        assert second is not None and second.url != first.url
        assert second.url == next(u for u in router.ring.walk(key)
                                  if u != first.url)
        third = router._pick(affinity_key=key,
                             exclude={first.url, second.url})
        assert third.url == next(u for u in router.ring.walk(key)
                                 if u not in (first.url, second.url))

    def test_walk_always_places_when_candidates_exist(self):
        """CHWBL never refuses: for any load vector some candidate sits
        under ceil(c*(L+1)/n) (pigeonhole), so an affinity pick with live
        replicas always returns one."""
        import random
        rng = random.Random(7)
        router = _router(balance_factor=1.0)
        for _ in range(200):
            for r in router.replicas:
                r.inflight = rng.randrange(0, 30)
            assert router._pick(affinity_key=b"k") is not None

    def test_no_key_falls_back_to_least_inflight(self):
        router = _router()
        router.replicas[2].inflight = 0
        for r in router.replicas[:2]:
            r.inflight = 5
        router.replicas[3].inflight = 5
        assert router._pick(affinity_key=None) is router.replicas[2]
        assert router.affinity_requests_total == 0

    def test_least_inflight_byte_identical_to_pre_affinity_router(self):
        """Acceptance pin: the default policy reproduces the pre-PR
        algorithm choice-for-choice — min inflight, ties broken by a
        0-based round-robin counter over the tied list in replica order —
        across a scripted sequence of loads, exclusions, and health flips.
        """
        import itertools

        router = _router(policy="least-inflight")
        legacy_rr = itertools.count()

        def legacy_pick(replicas, exclude=None, include_unhealthy=False):
            healthy = [r for r in replicas
                       if (r.healthy or include_unhealthy)
                       and (not exclude or r.url not in exclude)]
            if not healthy:
                return None
            least = min(r.inflight for r in healthy)
            tied = [r for r in healthy if r.inflight == least]
            return tied[next(legacy_rr) % len(tied)]

        script = [
            dict(loads=[0, 0, 0, 0]),
            dict(loads=[0, 0, 0, 0]),
            dict(loads=[2, 0, 1, 0]),
            dict(loads=[2, 0, 1, 0], exclude={URLS[1]}),
            dict(loads=[1, 1, 1, 1], unhealthy={URLS[0]}),
            dict(loads=[3, 3, 3, 3], unhealthy={URLS[0]},
                 include_unhealthy=True),
            dict(loads=[0, 5, 0, 5]),
            dict(loads=[0, 5, 0, 5]),
            dict(loads=[0, 5, 0, 5], exclude={URLS[0], URLS[2]}),
        ]
        for step in script:
            for r, load in zip(router.replicas, step["loads"]):
                r.inflight = load
                r.healthy = r.url not in step.get("unhealthy", ())
            expect = legacy_pick(router.replicas,
                                 exclude=step.get("exclude"),
                                 include_unhealthy=step.get(
                                     "include_unhealthy", False))
            got = router._pick(exclude=step.get("exclude"),
                               include_unhealthy=step.get(
                                   "include_unhealthy", False))
            assert got is expect, step

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="routing_policy"):
            Router(URLS, routing_policy="round-robin")
        with pytest.raises(ValueError, match="balance_factor"):
            Router(URLS, routing_policy="prefix-affinity",
                   balance_factor=0.5)


class TestAffinityKey:
    def test_session_id_beats_user_beats_prompt(self):
        router = _router()
        body = (b'{"prompt": "abc", "user": "u1", "session_id": "s1"}')
        assert router._affinity_key(body) == \
            b"sticky:session_id:s1"
        body = b'{"prompt": "abc", "user": "u1"}'
        assert router._affinity_key(body) == \
            b"sticky:user:u1"

    def test_prompt_prefix_windows(self):
        router = _router(affinity_prefix_len=4)
        # token-array prompt: first N ids
        assert router._affinity_key(
            b'{"prompt": [5, 6, 7, 8, 9, 10]}') == b"tokens:5,6,7,8"
        # text prompt: first 4*N utf-8 bytes
        key = router._affinity_key(b'{"prompt": "abcdefghijklmnopqrstuvwx"}')
        assert key == b"text:abcdefghijklmnop"
        # chat: serialized messages prefix (shared system prompts collide
        # into the same key, unrelated sessions with different prompts
        # diverge once past the boilerplate)
        k1 = router._affinity_key(
            b'{"messages": [{"role": "user", "content": "hi"}]}')
        assert k1 is not None and k1.startswith(b"chat:")

    def test_unparseable_or_keyless_bodies_yield_none(self):
        router = _router()
        assert router._affinity_key(b"") is None
        assert router._affinity_key(b"not json") is None
        assert router._affinity_key(b'[1, 2]') is None
        assert router._affinity_key(b'{"n": 1}') is None
        # bool session_id is not a usable scalar key
        assert router._affinity_key(b'{"session_id": true, "n": 1}') is None

    def test_least_inflight_policy_never_peeks(self):
        router = _router(policy="least-inflight")
        assert router._affinity_key(b'{"session_id": "s"}') is None


# ---------------------------------------------------------------------------
# aiohttp-level: streaming stickiness, churn remap, metrics aggregation
# ---------------------------------------------------------------------------

async def _recording_replica(extra_metrics=""):
    """A stand-in engine replica that records served completion requests
    (body + forwarded ``x-kgct-request-id``) and streams an SSE body (so
    stickiness is proven on the STREAMING proxy path — the body-peek must
    not break passthrough). Its ``/debug/trace`` mimics a real
    api_server: a lifecycle span per served request id, exported through
    the real RequestTracer — what the router's merged fleet trace
    fetches."""
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.observability.trace import RequestTracer
    from kubernetes_gpu_cluster_tpu.serving.errors import REQUEST_ID_HEADER

    served = []
    tracer = RequestTracer()

    async def health(request):
        return aioweb.json_response({"status": "ok"})

    async def metrics(request):
        return aioweb.Response(
            text="# TYPE kgct_requests_total counter\n"
                 f"kgct_requests_total {len(served)}\n" + extra_metrics,
            content_type="text/plain")

    async def completions(request):
        rid = request.headers.get(REQUEST_ID_HEADER, "")
        served.append({"body": await request.json(), "request_id": rid,
                       "headers": {k.lower(): v
                                   for k, v in request.headers.items()}})
        if rid:
            tracer.emit("arrival", rid, prompt_tokens=1)
            tracer.emit("first_token", rid, ttft_ms=1.0)
            tracer.emit("finish", rid, outcome="finished")
        resp = aioweb.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        await resp.write(b'data: {"text": "tok"}\n\n')
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    async def debug_trace(request):
        return aioweb.json_response(tracer.export_perfetto())

    app = aioweb.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/trace", debug_trace)
    app.router.add_post("/v1/completions", completions)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, f"http://127.0.0.1:{runner.addresses[0][1]}", served


async def _start_router(router):
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(router.build_app()))
    await client.start_server()
    return client


class TestStreamedStickiness:
    def test_session_requests_stream_through_one_replica(self):
        async def scenario():
            a_runner, a_url, a_served = await _recording_replica()
            b_runner, b_url, b_served = await _recording_replica()
            router = Router([a_url, b_url], health_interval_s=9999,
                            routing_policy="prefix-affinity")
            client = await _start_router(router)
            try:
                for i in range(4):
                    r = await client.post(
                        "/v1/completions",
                        json={"prompt": f"turn {i} of this conversation",
                              "session_id": "conv-42", "stream": True})
                    assert r.status == 200
                    body = await r.read()
                    assert b"[DONE]" in body      # stream passed through
                # All four landed on ONE replica (whichever owns the key).
                assert sorted([len(a_served), len(b_served)]) == [0, 4]
                assert router.affinity_hits_total == 4
                # A different session may land elsewhere, but is also
                # sticky to wherever it lands.
                for i in range(2):
                    await client.post(
                        "/v1/completions",
                        json={"prompt": "x", "session_id": "conv-43"})
                assert (len(a_served), len(b_served)) in (
                    (6, 0), (0, 6), (4, 2), (2, 4))
            finally:
                await client.close()
                await a_runner.cleanup()
                await b_runner.cleanup()
        asyncio.run(scenario())


@pytest.mark.chaos
class TestReplicaDownRemap:
    def test_downed_replica_keys_remap_then_return(self):
        """KGCT_FAULT replica_down: the health probe of the ring owner is
        forced down; its keys deterministically remap to the ring
        successor; clearing the fault restores the owner and the keys come
        home. The drain/429 machinery is untouched (other keys never
        move)."""
        async def scenario():
            a_runner, a_url, _ = await _recording_replica()
            b_runner, b_url, _ = await _recording_replica()
            router = Router([a_url, b_url], health_interval_s=9999,
                            routing_policy="prefix-affinity")
            client = await _start_router(router)
            try:
                key = b"sticky:session_id:chaos"
                owner_url = router.ring.owner(key)
                own_idx = [r.url for r in router.replicas].index(owner_url)
                other_url = [u for u in (a_url, b_url) if u != owner_url][0]
                other_key = next(
                    k for k in (f"probe-{i}".encode() for i in range(64))
                    if router.ring.owner(k) == other_url)
                assert router._pick(affinity_key=key).url == owner_url

                configure_faults(f"replica_down:value={own_idx}")
                for r in router.replicas:
                    await router._check(r, startup=True)
                assert not router.replicas[own_idx].healthy
                # Owned keys remap to the survivor...
                assert router._pick(affinity_key=key).url == other_url
                assert router.ring_remaps_total == 1
                # ...other keys never move (only K/N remap on churn).
                assert router._pick(affinity_key=other_key).url == other_url

                configure_faults(None)
                for r in router.replicas:
                    await router._check(r)
                assert router.replicas[own_idx].healthy
                assert router._pick(affinity_key=key).url == owner_url

                # The fire budget is consumed ONLY by the targeted
                # replica's probes: with times=1, probing every OTHER
                # replica first must not burn the single fire.
                router.replicas[own_idx].benched_until = 0.0
                configure_faults(f"replica_down:value={own_idx},times=1")
                for r in router.replicas:
                    if r is not router.replicas[own_idx]:
                        await router._check(r)
                assert all(r.healthy for r in router.replicas)
                await router._check(router.replicas[own_idx], startup=True)
                assert not router.replicas[own_idx].healthy
            finally:
                await client.close()
                await a_runner.cleanup()
                await b_runner.cleanup()
        asyncio.run(scenario())


class TestRouterMetricsAggregation:
    def test_replica_locality_gauges_zero_and_absent_safe(self):
        """The router folds each replica's scraped prefix-cache hit ratio
        and swapped count into router-owned labeled gauges. A replica
        whose engine predates the series (or was skipped) still gets a 0.0
        sample — a fresh scrape is nan-free and needs no existence check —
        and the affinity counters render zeros on a fresh least-inflight
        router."""
        async def scenario():
            a_runner, a_url, _ = await _recording_replica(
                extra_metrics=(
                    "# TYPE kgct_prefix_cache_hit_ratio gauge\n"
                    "kgct_prefix_cache_hit_ratio 0.75\n"
                    "# TYPE kgct_num_swapped gauge\n"
                    "kgct_num_swapped 2\n"))
            b_runner, b_url, _ = await _recording_replica()  # no series
            router = Router([a_url, b_url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                r = await client.get("/metrics")
                text = await r.text()
                _assert_valid_exposition(text)

                def val(name, url):
                    # Per-replica gauges may carry a role label after the
                    # replica label (disaggregated pools).
                    [line] = [l for l in text.splitlines()
                              if l.startswith(f'{name}{{replica="{url}"')]
                    return float(line.rpartition(" ")[2])

                assert val("kgct_router_replica_prefix_cache_hit_ratio",
                           a_url) == 0.75
                assert val("kgct_router_replica_prefix_cache_hit_ratio",
                           b_url) == 0.0
                assert val("kgct_router_replica_num_swapped", a_url) == 2.0
                assert val("kgct_router_replica_num_swapped", b_url) == 0.0
                # Pool-role label: a non-disaggregated router labels every
                # replica gauge role="both" (the pre-disaggregation
                # behavior, one spelling fleet-wide).
                assert (f'kgct_router_replica_healthy{{replica="{a_url}",'
                        'role="both"} 1') in text
                # Affinity accounting: present and zero-safe even on the
                # default policy with zero affinity-keyed traffic.
                assert "kgct_router_affinity_hit_ratio 0.0" in text
                assert "kgct_router_ring_remaps_total 0" in text
                assert val("kgct_router_affinity_overflow_total",
                           a_url) == 0.0
                assert ('kgct_router_policy{policy="least-inflight"} 1'
                        in text)
                # Fleet-trace scrape accounting: present and zero on a
                # fresh router.
                assert "kgct_router_trace_scrape_errors_total 0" in text
            finally:
                await client.close()
                await a_runner.cleanup()
                await b_runner.cleanup()
        asyncio.run(scenario())


class TestRouterRequestId:
    def test_id_minted_forwarded_and_echoed(self):
        """The correlation-id contract (satellite 1): every router response
        carries x-kgct-request-id — minted when absent, honored when the
        inbound header is valid — and the SAME id is forwarded upstream so
        the replica can adopt it as its engine request id."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)

        async def scenario():
            a_runner, a_url, a_served = await _recording_replica()
            router = Router([a_url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                # Minted: no inbound header.
                r = await client.post("/v1/completions",
                                      json={"prompt": "x"})
                assert r.status == 200
                minted = r.headers[REQUEST_ID_HEADER]
                assert minted.startswith("req-")
                assert a_served[0]["request_id"] == minted   # forwarded
                # Honored: a valid inbound id passes through end-to-end.
                r2 = await client.post(
                    "/v1/completions", json={"prompt": "y"},
                    headers={REQUEST_ID_HEADER: "req-client-42"})
                assert r2.headers[REQUEST_ID_HEADER] == "req-client-42"
                assert a_served[1]["request_id"] == "req-client-42"
                # Invalid inbound (spaces) is replaced by a fresh mint.
                r3 = await client.post(
                    "/v1/completions", json={"prompt": "z"},
                    headers={REQUEST_ID_HEADER: "bad id"})
                assert r3.headers[REQUEST_ID_HEADER].startswith("req-")
            finally:
                await client.close()
                await a_runner.cleanup()
        asyncio.run(scenario())

    def test_error_responses_carry_id(self):
        """429/503-class rejections are exactly where correlation matters
        most (satellite 1's bugfix): a router with no healthy replicas
        still stamps the id on its 503."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)

        async def scenario():
            # Nothing listens on this port: the startup probe benches it.
            router = Router(["http://127.0.0.1:1"], health_interval_s=9999,
                            connect_retries=0)
            client = await _start_router(router)
            try:
                r = await client.post(
                    "/v1/completions", json={"prompt": "x"},
                    headers={REQUEST_ID_HEADER: "req-err-1"})
                assert r.status in (502, 503)
                assert r.headers[REQUEST_ID_HEADER] == "req-err-1"
                r2 = await client.post("/v1/completions",
                                       json={"prompt": "x"})
                assert r2.status in (502, 503)
                assert r2.headers[REQUEST_ID_HEADER].startswith("req-")
            finally:
                await client.close()
        asyncio.run(scenario())


class TestMergedFleetTrace:
    def test_debug_trace_merges_router_and_replica_spans(self):
        """The tentpole's single-download contract: GET /debug/trace on the
        router returns ONE Perfetto doc with the router's spans (pid 1) and
        each replica's lifecycle spans (pid 2..N), correlated on the
        router-minted ids, with per-process name metadata."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)

        async def scenario():
            a_runner, a_url, _ = await _recording_replica()
            b_runner, b_url, _ = await _recording_replica()
            router = Router([a_url, b_url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                # One request pinned to each replica via least-inflight's
                # deterministic tie-break (inflight 0, seq 0 then 1).
                for rid in ("req-merge-a", "req-merge-b"):
                    r = await client.post(
                        "/v1/completions", json={"prompt": rid},
                        headers={REQUEST_ID_HEADER: rid})
                    assert r.status == 200
                r = await client.get("/debug/trace")
                assert r.status == 200
                doc = await r.json()
            finally:
                await client.close()
                await a_runner.cleanup()
                await b_runner.cleanup()

            evs = doc["traceEvents"]
            # Three processes, labeled: the router + both replicas.
            labels = {e["pid"]: e["args"]["name"] for e in evs
                      if e.get("name") == "process_name"}
            assert labels[1] == "kgct-router"
            assert {f"kgct-engine {a_url}", f"kgct-engine {b_url}"} == {
                labels[2], labels[3]}
            # Router spans AND replica spans share the minted ids.
            by_pid = {}
            for e in evs:
                if e.get("cat") == "request" and e.get("id"):
                    by_pid.setdefault(e["pid"], set()).add(e["id"])
            assert by_pid[1] == {"req-merge-a", "req-merge-b"}
            assert by_pid[2] | by_pid[3] == {"req-merge-a", "req-merge-b"}
            # The router's per-request instants carry pick attribution.
            picks = [e for e in evs if e.get("name") == "pick"]
            assert picks and all(e["pid"] == 1 for e in picks)
            assert {e["args"]["replica"] for e in picks} == {a_url, b_url}
            # Timestamps rebased onto one timeline: all non-meta ts >= 0.
            assert all(e["ts"] >= 0 for e in evs if "ts" in e)
            import json as _json
            _json.dumps(doc)               # wire-serializable

    def test_replica_without_trace_endpoint_is_skipped_and_counted(self):
        """A replica whose /debug/trace is missing (predates the feature)
        or stalls must not break the fleet download: it is skipped and
        counted, and the router's own spans still export."""
        from aiohttp import web as aioweb

        async def scenario():
            # Minimal replica: health only — /debug/trace 404s.
            async def health(request):
                return aioweb.json_response({"status": "ok"})

            app = aioweb.Application()
            app.router.add_get("/health", health)
            runner = aioweb.AppRunner(app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            url = f"http://127.0.0.1:{runner.addresses[0][1]}"
            router = Router([url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                r = await client.get("/debug/trace")
                assert r.status == 200
                doc = await r.json()
                assert router.trace_scrape_errors_total == 1
                labels = [e["args"]["name"] for e in doc["traceEvents"]
                          if e.get("name") == "process_name"]
                assert labels == ["kgct-router"]
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())

    def test_flightrecorder_endpoint_exports_spans_and_snapshots(self):
        async def scenario():
            a_runner, a_url, _ = await _recording_replica()
            router = Router([a_url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                await client.post("/v1/completions", json={"prompt": "x"})
                router.flight.maybe_snapshot()   # the health loop's call
                r = await client.get("/debug/flightrecorder")
                assert r.status == 200
                doc = await r.json()
            finally:
                await client.close()
                await a_runner.cleanup()
            kinds = {e["kind"] for e in doc["events"]}
            assert {"arrival", "pick", "finish", "snapshot"} <= kinds
            snap = next(e for e in doc["events"] if e["kind"] == "snapshot")
            assert a_url in snap["inflight"]
            assert snap["healthy"] == [a_url]
        asyncio.run(scenario())


# ---------------------------------------------------------------------------
# The multi-engine bench phase (real engines behind the real router)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
class TestRemapContractSoak:
    """Live CHWBL remap-contract soak (ROADMAP 7a, software half): under
    ``replica_down`` churn across several cycles, ONLY the downed
    replica's ~K/N affinity keys remap (each to a deterministic ring
    successor) and every owner returns home on recovery — the contract
    that makes drain migration and failover land where the parked KV
    lives. Engine-free: stub replicas, real router probes + pick seam."""

    def test_churn_cycles_remap_only_owned_keys_and_recover(self):
        N, K, CYCLES = 6, 96, 4

        async def scenario():
            runners, urls = [], []
            for _ in range(N):
                runner, url, _ = await _recording_replica()
                runners.append(runner)
                urls.append(url)
            router = Router(urls, health_interval_s=9999,
                            routing_policy="prefix-affinity")
            client = await _start_router(router)
            keys = [f"soak-session-{i}".encode() for i in range(K)]
            try:
                def owners():
                    return {k: router._pick(affinity_key=k).url
                            for k in keys}

                baseline = owners()
                by_owner: dict = {}
                for k, u in baseline.items():
                    by_owner.setdefault(u, []).append(k)
                # CHWBL spreads the keys: every replica owns some, nobody
                # owns a constant factor more than fair share (the load
                # bound, not vnode luck, is what bounds skew — but vnode
                # placement must not be degenerate either).
                assert len(by_owner) == N
                assert max(len(v) for v in by_owner.values()) <= 3 * K // N

                for cycle in range(CYCLES):
                    down = cycle % N
                    down_url = urls[down]
                    configure_faults(f"replica_down:value={down}")
                    for r in router.replicas:
                        await router._check(r, startup=True)
                    assert not router.replicas[down].healthy
                    churned = owners()
                    moved = {k for k in keys
                             if churned[k] != baseline[k]}
                    # The remap contract: exactly the downed replica's
                    # keys move — ~K/N, never a full reshuffle — and each
                    # lands on ITS key's ring successor (where a drain
                    # push / failover re-dispatch would look for it).
                    assert moved == set(by_owner[down_url]), \
                        f"cycle {cycle}: non-owned keys remapped"
                    assert 0 < len(moved) <= 3 * K // N
                    for k in moved:
                        want = next(
                            u for u in router.ring.walk(k)
                            if u != down_url)
                        assert churned[k] == want
                    # Recovery: the owner returns, every key comes home.
                    configure_faults(None)
                    router.replicas[down].benched_until = 0.0
                    for r in router.replicas:
                        await router._check(r)
                    assert router.replicas[down].healthy
                    assert owners() == baseline, \
                        f"cycle {cycle}: owners did not return on recovery"
            finally:
                configure_faults(None)
                await client.close()
                for runner in runners:
                    await runner.cleanup()
        asyncio.run(scenario())


@pytest.mark.slow
class TestRouterBenchPhase:
    def test_affinity_concentrates_locality_over_least_inflight(self):
        """The KGCT_BENCH_ROUTER A/B end-to-end: the affinity arm routes
        every session to its ring owner (hit ratio 1.0, zero remaps), the
        owner replica's prefix-cache hit ratio strictly exceeds the
        least-inflight arm's best, and the headline ratio is present. The
        routing-count assertions are deterministic; wall-clock only gets a
        loose sanity bound (this is the bench's job to measure)."""
        import bench

        out = bench._measure_router()
        li, aff = out["least_inflight"], out["prefix_affinity"]
        assert aff["affinity_hit_ratio"] == 1.0
        assert aff["ring_remaps"] == 0
        li_best = max((p["hit_ratio"] or 0.0) for p in li["per_replica"])
        owner_ratios = [p["hit_ratio"] for p in aff["per_replica"]
                        if p["requests"] > 0]
        assert owner_ratios and min(owner_ratios) > li_best
        # Sessions scattered under least-inflight (both replicas served)...
        assert all(p["requests"] > 0 for p in li["per_replica"])
        assert out["warm_ttft_ratio"] is not None
        assert out["warm_ttft_ratio"] < 1.5   # loose: not a perf pin


class TestDisaggRouting:
    """Disaggregated prefill/decode at the ROUTER layer (engine-free):
    prefill-pool picks flow through the one _pick seam on a dedicated
    ring, the forwarded header names the picked prefill replica (and
    client-supplied values are stripped), and one scrape separates the
    pools by role."""

    PF_URLS = [f"http://prefill-{i}:8000" for i in range(2)]

    def test_prefill_pick_is_prefix_affine_even_under_least_inflight(self):
        router = Router(list(URLS), routing_policy="least-inflight",
                        prefill_urls=list(self.PF_URLS))
        key = b"text:some prompt prefix"
        owner = router.prefill_ring.owner(key)
        for _ in range(5):
            picked = router._pick(affinity_key=key,
                                  pool=router.prefill_replicas,
                                  ring=router.prefill_ring)
            assert picked.url == owner
        # Prefill-pool picks never pollute the MAIN pool's affinity
        # accounting.
        assert router.affinity_requests_total == 0
        # Dead owner: keys remap to the ring successor, deterministic.
        dead = next(r for r in router.prefill_replicas if r.url == owner)
        dead.healthy = False
        picked = router._pick(affinity_key=key,
                              pool=router.prefill_replicas,
                              ring=router.prefill_ring)
        assert picked.url == next(u for u in router.prefill_ring.walk(key)
                                  if u != owner)
        assert router.ring_remaps_total == 0   # main-pool counter untouched

    def test_prefill_pool_bounded_load_spills_off_a_hot_owner(self):
        """A prefill replica holding outstanding pull slots overflows the
        CHWBL bound to the ring successor — live only because proxy()
        accounts the pull slot on the picked replica (at permanent
        inflight 0 the bound is never exceeded and a hot prefix would pin
        100% of handoffs to one replica)."""
        router = Router(list(URLS), routing_policy="least-inflight",
                        prefill_urls=list(self.PF_URLS))
        key = b"text:some prompt prefix"
        owner_url = router.prefill_ring.owner(key)
        owner = next(r for r in router.prefill_replicas
                     if r.url == owner_url)
        owner.inflight = 10            # outstanding handoff pull slots
        picked = router._pick(affinity_key=key,
                              pool=router.prefill_replicas,
                              ring=router.prefill_ring)
        assert picked.url != owner_url
        assert router._pick_info["pick"] == "affinity_overflow"

    def test_proxy_accounts_the_prefill_pull_slot(self):
        """proxy() holds one inflight slot on the picked prefill replica
        for the request's lifetime and always drains it."""
        async def scenario():
            pf_runner, pf_url, _ = await _recording_replica()
            dc_runner, dc_url, _ = await _recording_replica()
            router = Router([dc_url], health_interval_s=9999,
                            prefill_urls=[pf_url])
            client = await _start_router(router)
            pf = router.prefill_replicas[0]
            seen = []
            orig = router._session.request

            def spy(method, url, **kw):
                seen.append(pf.inflight)
                return orig(method, url, **kw)

            router._session.request = spy
            try:
                r = await client.post("/v1/completions",
                                      json={"prompt": "x"})
                assert r.status == 200
                assert seen[-1] == 1   # held while forwarding downstream
                await r.read()         # drain the relay to its finally
                await asyncio.sleep(0.05)
                assert pf.inflight == 0            # drained at completion
            finally:
                await client.close()
                await pf_runner.cleanup()
                await dc_runner.cleanup()
        asyncio.run(scenario())

    def test_header_forwarded_and_client_value_stripped(self):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFILL_URL_HEADER)

        async def scenario():
            pf_runner, pf_url, _ = await _recording_replica()
            dc_runner, dc_url, dc_served = await _recording_replica()

            # Capture the headers the decode replica actually receives.
            router = Router([dc_url], health_interval_s=9999,
                            prefill_urls=[pf_url])
            client = await _start_router(router)
            seen = []
            orig = router._session.request

            def spy(method, url, **kw):
                seen.append(kw.get("headers") or {})
                return orig(method, url, **kw)

            router._session.request = spy
            try:
                r = await client.post(
                    "/v1/completions", json={"prompt": "x"},
                    headers={PREFILL_URL_HEADER: "http://evil:1"})
                assert r.status == 200
                fwd = seen[-1]
                assert fwd[PREFILL_URL_HEADER] == pf_url
                # /v1/models (no body/prompt) never carries the header.
                r = await client.get("/v1/models")
                assert PREFILL_URL_HEADER not in (seen[-1] or {})
                # The pick span carries the pool attribution.
                picks = [e for e in router.tracer.events()
                         if e.kind == "pick"
                         and e.args.get("pool") == "prefill"]
                assert picks and picks[0].args["replica"] == pf_url
            finally:
                await client.close()
                await pf_runner.cleanup()
                await dc_runner.cleanup()
        asyncio.run(scenario())

    def test_metrics_and_health_separate_pools_by_role(self):
        async def scenario():
            pf_runner, pf_url, _ = await _recording_replica()
            dc_runner, dc_url, _ = await _recording_replica()
            router = Router([dc_url], health_interval_s=9999,
                            prefill_urls=[pf_url])
            client = await _start_router(router)
            try:
                r = await client.get("/metrics")
                text = await r.text()
                _assert_valid_exposition(text)
                assert (f'kgct_router_replica_healthy{{replica="{dc_url}",'
                        'role="decode"} 1') in text
                assert (f'kgct_router_replica_healthy{{replica="{pf_url}",'
                        'role="prefill"} 1') in text
                # Locality gauges cover BOTH pools, zero-safe.
                assert (f'kgct_router_replica_prefix_cache_hit_ratio'
                        f'{{replica="{pf_url}",role="prefill"}} 0.0') \
                    in text
                r = await client.get("/health")
                body = await r.json()
                assert body["replicas"][pf_url]["role"] == "prefill"
                assert body["replicas"][dc_url]["role"] == "decode"
            finally:
                await client.close()
                await pf_runner.cleanup()
                await dc_runner.cleanup()
        asyncio.run(scenario())

    def test_no_healthy_prefill_pool_degrades_to_no_header(self):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFILL_URL_HEADER)

        async def scenario():
            dc_runner, dc_url, dc_served = await _recording_replica()
            # Nothing listens on the prefill URL: the startup probe
            # benches it; completions must still flow, headerless.
            router = Router([dc_url], health_interval_s=9999,
                            prefill_urls=["http://127.0.0.1:1"])
            client = await _start_router(router)
            seen = []
            orig = router._session.request

            def spy(method, url, **kw):
                seen.append(kw.get("headers") or {})
                return orig(method, url, **kw)

            router._session.request = spy
            try:
                r = await client.post("/v1/completions",
                                      json={"prompt": "x"})
                assert r.status == 200
                assert PREFILL_URL_HEADER not in seen[-1]
            finally:
                await client.close()
                await dc_runner.cleanup()
        asyncio.run(scenario())

    def test_multi_sequence_requests_skip_the_prefill_pick(self):
        """n/best_of > 1 requests fan out through the replica's _run_n
        BEFORE its handoff block — a prefill pick would hold a phantom
        pull slot forever. Only positively multi-sequence bodies skip;
        everything else (absent, n=1, unparseable) stays eligible."""
        def ok(body):
            return Router._handoff_eligible(Router._parse_json_dict(body))
        assert not ok(b'{"prompt": "x", "n": 2}')
        assert not ok(b'{"prompt": "x", "best_of": 3}')
        assert ok(b'{"prompt": "x"}')
        assert ok(b'{"prompt": "x", "n": 1}')
        assert ok(b'{"prompt": "x", "n": 1, "best_of": 1}')
        assert ok(b'{"prompt": "x", "n": "zzz"}')   # replica's 400 to give
        assert ok(b'not json at all')
        assert ok(b'[1, 2, 3]')

    def test_flight_snapshot_covers_both_pools(self):
        """Flight-recorder fleet snapshots report inflight/health for the
        prefill pool too, not just the main pool."""
        router = Router(list(URLS), routing_policy="least-inflight",
                        prefill_urls=list(self.PF_URLS))
        router.prefill_replicas[0].inflight = 3
        router.prefill_replicas[1].healthy = False
        snap = router._flight_snapshot()
        for url in (*URLS, *self.PF_URLS):
            assert url in snap["inflight"]
        assert snap["inflight"][self.PF_URLS[0]] == 3
        assert self.PF_URLS[0] in snap["healthy"]
        assert self.PF_URLS[1] not in snap["healthy"]


class TestTierAwarePicks:
    """ROADMAP 3c: interactive-tier picks deprioritize batch-saturated
    replicas using the per-tier /health inflight ledger the health probe
    already scrapes — engine-free, all inside the one _pick seam."""

    def _router(self, n=3):
        from kubernetes_gpu_cluster_tpu.config.qos import parse_qos_tiers
        return _router(policy="least-inflight",
                       urls=[f"http://r{i}:8000" for i in range(n)],
                       qos_tiers=parse_qos_tiers("default"))

    def test_interactive_pick_avoids_batch_saturated_replica(self):
        router = self._router()
        router.replicas[0].tier_inflight = {"interactive": 0, "batch": 5}
        # Total inflight ties at 0 everywhere: the interactive pick must
        # rotate over the two batch-free replicas only.
        urls = {router._pick(pick_tier="interactive").url
                for _ in range(6)}
        assert urls == {"http://r1:8000", "http://r2:8000"}
        assert router._pick_info.get("tier_deprioritized") == 1

    def test_batch_pick_keeps_legacy_rotation(self):
        """A lowest-tier pick has no lower tier to avoid: the legacy
        round-robin covers every replica, batch-saturated included."""
        router = self._router()
        router.replicas[0].tier_inflight = {"batch": 5}
        urls = {router._pick(pick_tier="batch").url for _ in range(3)}
        assert urls == {r.url for r in router.replicas}

    def test_tier_none_byte_identical_rotation(self):
        """QoS-off picks (tier None) ignore the ledger entirely — the
        pre-existing least-inflight behavior, ledger or not."""
        router = self._router()
        router.replicas[0].tier_inflight = {"batch": 99}
        urls = {router._pick().url for _ in range(3)}
        assert urls == {r.url for r in router.replicas}

    def test_total_inflight_stays_primary(self):
        """The tie-break is SECONDARY: a genuinely less-loaded replica
        wins even when its ledger shows batch work (that work is
        engine-preemptible for the interactive request; an extra live
        stream is not)."""
        router = self._router()
        router.replicas[0].tier_inflight = {"batch": 9}
        router.replicas[1].inflight = 1
        router.replicas[2].inflight = 1
        assert router._pick(pick_tier="interactive").url == "http://r0:8000"

    def test_health_probe_scrapes_the_ledger(self):
        """The /health probe body's qos_tiers dict lands on the Replica —
        no extra request, best-effort on replicas without the field."""
        import aiohttp

        async def run():
            from kubernetes_gpu_cluster_tpu.config.qos import parse_qos_tiers
            from aiohttp import web as aioweb

            async def health(request):
                return aioweb.json_response(
                    {"status": "ok", "qos_tiers": {"batch": 7,
                                                   "interactive": 1}})
            app = aioweb.Application()
            app.router.add_get("/health", health)
            runner = aioweb.AppRunner(app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            url = f"http://127.0.0.1:{runner.addresses[0][1]}"
            router = Router([url], health_interval_s=9999,
                            qos_tiers=parse_qos_tiers("default"))
            router._session = aiohttp.ClientSession()
            try:
                await router._check(router.replicas[0], startup=True)
                assert router.replicas[0].tier_inflight == {
                    "batch": 7, "interactive": 1}
            finally:
                await router._session.close()
                await runner.cleanup()
        asyncio.run(run())


class TestPrefixSourceHint:
    """Fleet-wide prefix cache, router half: an overflow/remap pick names
    the ring owner in x-kgct-prefix-source so the chosen replica can pull
    the owner's cached prefix — engine-free pins of the hint derivation
    plus one proxied header-forwarding check."""

    def _owner_and_other(self, router, key):
        owner_url = router.ring.owner(key)
        owner = next(r for r in router.replicas if r.url == owner_url)
        other = next(r for r in router.replicas if r.url != owner_url)
        return owner, other

    def test_overflow_pick_names_the_owner(self):
        router = _router(urls=URLS[:2], balance_factor=1.0)
        key = b"hot-prefix"
        owner, other = self._owner_and_other(router, key)
        owner.inflight = 5                      # over the CHWBL bound
        picked = router._pick(affinity_key=key)
        assert picked.url == other.url
        assert router._pick_info["pick"] == "affinity_overflow"
        assert router._prefix_source(dict(router._pick_info),
                                     picked.url) == owner.url

    def test_affinity_hit_carries_no_hint(self):
        router = _router(urls=URLS[:2])
        key = b"cold-prefix"
        picked = router._pick(affinity_key=key)
        assert router._pick_info["pick"] == "affinity_hit"
        assert router._prefix_source(dict(router._pick_info),
                                     picked.url) is None

    def test_downed_owner_is_not_named(self):
        """A remap whose owner is DOWN must not be named: the pull would
        burn a doomed connect before degrading — worse than recomputing."""
        router = _router(urls=URLS[:2])
        key = b"hot-prefix-2"
        owner, other = self._owner_and_other(router, key)
        owner.healthy = False
        picked = router._pick(affinity_key=key)
        assert picked.url == other.url
        assert router._pick_info["pick"] == "affinity_remap"
        assert router._prefix_source(dict(router._pick_info),
                                     picked.url) is None

    def test_excluded_healthy_owner_is_named(self):
        """A remap because the owner was EXCLUDED (this request's retry
        walk) still names it: the owner is alive and its cache is warm."""
        router = _router(urls=URLS[:2])
        key = b"hot-prefix-3"
        owner, other = self._owner_and_other(router, key)
        picked = router._pick(affinity_key=key, exclude={owner.url})
        assert picked.url == other.url
        assert router._pick_info["pick"] == "affinity_remap"
        assert router._prefix_source(dict(router._pick_info),
                                     picked.url) == owner.url

    def test_overflowed_pick_forwards_the_hint_upstream(self):
        """Through the real proxy: the over-bound owner's url rides
        x-kgct-prefix-source to the chosen replica, and a client-supplied
        value is stripped (router-owned header)."""
        async def scenario():
            a_runner, a_url, a_served = await _recording_replica()
            b_runner, b_url, b_served = await _recording_replica()
            router = Router([a_url, b_url], health_interval_s=9999,
                            routing_policy="prefix-affinity",
                            balance_factor=1.0)
            client = await _start_router(router)
            try:
                from kubernetes_gpu_cluster_tpu.serving.errors import \
                    PREFIX_SOURCE_HEADER
                body = {"prompt": "shared prefix body", "stream": False}
                r = await client.post(
                    "/v1/completions", json=body,
                    headers={PREFIX_SOURCE_HEADER: "http://evil:1"})
                assert r.status == 200
                served = (a_served + b_served)[-1]
                # Affinity hit: no hint, and the client's value is gone.
                assert PREFIX_SOURCE_HEADER not in served["headers"]
                # Saturate the owner so the next pick overflows.
                owner_url = router.ring.owner(
                    router._affinity_key_from_obj(body))
                owner = next(rep for rep in router.replicas
                             if rep.url == owner_url)
                other_served = b_served if owner_url == a_url else a_served
                owner.inflight = 5
                r = await client.post("/v1/completions", json=body)
                assert r.status == 200
                served = other_served[-1]      # the overflow target
                assert served["headers"].get(
                    PREFIX_SOURCE_HEADER) == owner_url
            finally:
                await client.close()
                await a_runner.cleanup()
                await b_runner.cleanup()
        asyncio.run(scenario())
