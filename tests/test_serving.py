"""Serving layer: tokenizer, detokenizer stop handling, OpenAI API server,
router — end-to-end over the real engine on the CPU mesh (debug-tiny)."""

import asyncio
import json
import time

import aiohttp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
from kubernetes_gpu_cluster_tpu.serving.router import Router
from kubernetes_gpu_cluster_tpu.serving.tokenizer import (
    ByteTokenizer, IncrementalDetokenizer)


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        text = "hello, TPU! héllo é世界"
        ids = tok.encode(text)
        assert ids[0] == tok.BOS
        assert tok.decode(ids) == text

    def test_specials_skipped(self):
        tok = ByteTokenizer()
        assert tok.decode([tok.BOS, ord("h") + 3, tok.EOS]) == "h"


class TestIncrementalDetokenizer:
    def test_streams_deltas(self):
        tok = ByteTokenizer(add_bos=False)
        d = IncrementalDetokenizer(tok)
        out = d.push(tok.encode("hel")) + d.push(tok.encode("lo"))
        out += d.push([], final=True)
        assert out == "hello"

    def test_stop_string_across_pushes(self):
        tok = ByteTokenizer(add_bos=False)
        d = IncrementalDetokenizer(tok, stop=["END"])
        a = d.push(tok.encode("abcE"))
        assert "E" not in a          # held back: could start "END"
        b = d.push(tok.encode("NDxyz"))
        assert d.stopped
        assert a + b == "abc"

    def test_stop_string_not_matched_releases_holdback(self):
        tok = ByteTokenizer(add_bos=False)
        d = IncrementalDetokenizer(tok, stop=["END"])
        a = d.push(tok.encode("abcEN"))
        b = d.push(tok.encode("Q"), final=True)
        assert not d.stopped
        assert a + b == "abcENQ"

    def test_partial_utf8_held_back(self):
        tok = ByteTokenizer(add_bos=False)
        d = IncrementalDetokenizer(tok)
        raw = "é".encode("utf-8")
        a = d.push([raw[0] + 3])
        b = d.push([raw[1] + 3], final=True)
        assert a + b == "é"


def _engine_config():
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=256,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(128, 256),
                                  decode_window=4))


_SERVER: dict = {}      # module-scope handle to the live APIServer (obs tests)


@pytest.fixture(scope="module")
def api_client():
    """One engine + server shared by the module (compiles once)."""
    loop = asyncio.new_event_loop()
    server = build_server(_engine_config(), tokenizer_path=None,
                          model_name="debug-tiny")
    _SERVER["api"] = server
    client = TestClient(TestServer(server.build_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client
    loop.run_until_complete(client.close())
    loop.close()


class TestAPIServer:
    def test_health_and_models(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.get("/health")
            assert r.status == 200
            assert (await r.json())["status"] == "ok"
            r = await client.get("/v1/models")
            data = await r.json()
            assert data["data"][0]["id"] == "debug-tiny"
        loop.run_until_complete(go())

    def test_completion_non_streaming(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "hello world", "max_tokens": 8, "temperature": 0.0})
            assert r.status == 200
            data = await r.json()
            assert data["object"] == "completion"
            assert data["usage"]["completion_tokens"] > 0
            assert isinstance(data["choices"][0]["text"], str)
            assert data["choices"][0]["finish_reason"] in ("stop", "length")
            return data
        d1 = loop.run_until_complete(go())
        d2 = loop.run_until_complete(go())
        # greedy determinism through the whole HTTP+engine stack
        assert d1["choices"][0]["text"] == d2["choices"][0]["text"]

    def test_completion_streaming_sse(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "stream me", "max_tokens": 8, "temperature": 0.0,
                "stream": True})
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            events = []
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: "):
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        break
                    events.append(json.loads(payload))
            assert events, "no SSE events"
            assert events[-1]["choices"][0]["finish_reason"] in ("stop", "length")
            text = "".join(e["choices"][0].get("text", "") for e in events)
            return text
        text = loop.run_until_complete(go())

        async def non_stream():
            r = await client.post("/v1/completions", json={
                "prompt": "stream me", "max_tokens": 8, "temperature": 0.0})
            return (await r.json())["choices"][0]["text"]
        assert text == loop.run_until_complete(non_stream())

    def test_chat_completion(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 6, "temperature": 0.0})
            assert r.status == 200
            data = await r.json()
            assert data["object"] == "chat.completion"
            assert "content" in data["choices"][0]["message"]
        loop.run_until_complete(go())

    def test_token_ids_prompt_and_errors(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [5, 6, 7], "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.post("/v1/completions", json={"max_tokens": 4})
            assert r.status == 400
            r = await client.post("/v1/completions", data=b"not json")
            assert r.status == 400
        loop.run_until_complete(go())

    def test_metrics_endpoint(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.get("/metrics")
            assert r.status == 200
            text = await r.text()
            assert "kgct_tokens_generated_total" in text
            assert "kgct_kv_pages_free" in text
            return text
        text = loop.run_until_complete(go())
        gen = [l for l in text.splitlines()
               if l.startswith("kgct_tokens_generated_total")]
        assert int(gen[0].split()[-1]) > 0   # previous tests generated tokens
        # Real histograms with filled buckets for the north-star latencies
        # (previous tests completed requests), validated structurally.
        _assert_valid_exposition(text)
        for fam in ("kgct_ttft_seconds", "kgct_tpot_seconds",
                    "kgct_queue_wait_seconds", "kgct_step_seconds",
                    "kgct_request_e2e_seconds", "kgct_batch_size_per_step"):
            assert f"# TYPE {fam} histogram" in text, fam
            assert f"{fam}_bucket" in text, f"{fam}: no observations"
        assert 'le="+Inf"' in text
        assert "kgct_step_phase_seconds_total" in text

    def test_prefix_cache_metrics_on_fresh_scrape(self, api_client):
        """ROADMAP item 2's gauge: kgct_prefix_cache_hit_ratio plus the
        hits/misses counters are PRESENT and nan-free even on an engine
        that never enabled prefix caching (zeros, not absent — dashboards
        must not need an existence check). The exposition validity
        (nan-free, contiguous families) is pinned by
        _assert_valid_exposition in test_metrics_endpoint above."""
        loop, client = api_client

        async def go():
            r = await client.get("/metrics")
            return await r.text()
        text = loop.run_until_complete(go())
        for name, typ in (("kgct_prefix_cache_hit_ratio", "gauge"),
                          ("kgct_prefix_cache_hits_total", "counter"),
                          ("kgct_prefix_cache_misses_total", "counter")):
            assert f"# TYPE {name} {typ}" in text, name
            [line] = [l for l in text.splitlines()
                      if l.startswith(name + " ")]
            value = float(line.split()[-1])
            assert value == value and value >= 0.0, line


def _parse_sample(line: str):
    """One exposition sample line -> (base_name, labels_dict, float_value)."""
    import re
    name_part, _, val = line.rpartition(" ")
    base, _, rest = name_part.partition("{")
    labels = dict(re.findall(r'(\w+)="([^"]*)"', rest))
    return base, labels, float(val)


def _assert_valid_exposition(text: str) -> None:
    """Prometheus text-format validity as strict parsers enforce it: at most
    one TYPE line per metric family with all of a family's samples contiguous
    (a family's block ends as soon as another family's line appears); every
    sample value finite (no nan, even on a freshly started server); histogram
    families structurally sound — per labelset, cumulative bucket counts
    monotone non-decreasing, the +Inf bucket equal to ``_count``, and a
    matching ``_sum``/``_count`` pair present."""
    import math

    closed: set[str] = set()
    current = None
    types: dict[str, str] = {}
    by_name: dict[str, list] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE"):
            parts = line.split()
            fam = parts[2]
            assert fam not in closed and fam != current, (
                f"duplicate TYPE for family {fam}")
            assert fam not in types, f"duplicate TYPE for family {fam}"
            types[fam] = parts[3]
            if current is not None:
                closed.add(current)
            current = fam
            continue
        if line.startswith("#"):
            continue
        base, labels, value = _parse_sample(line)
        assert not math.isnan(value), f"nan in exposition: {line!r}"
        by_name.setdefault(base, []).append((labels, value))
        fam = (current if current is not None and
               (base == current or base.startswith(current + "_"))
               else base)
        if fam != current:
            if current is not None:
                closed.add(current)
            current = fam
        assert fam not in closed, (
            f"samples of family {fam} are not contiguous: {line!r}")

    def cell_key(labels):
        return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))

    for fam, typ in types.items():
        if typ != "histogram":
            continue
        buckets = by_name.get(fam + "_bucket", [])
        sums = {cell_key(l): v for l, v in by_name.get(fam + "_sum", [])}
        counts = {cell_key(l): v for l, v in by_name.get(fam + "_count", [])}
        if not (buckets or sums or counts):
            continue    # labeled histogram with no observations yet: legal
        assert buckets and sums and counts, f"{fam}: incomplete histogram"
        series: dict = {}
        for labels, v in buckets:
            series.setdefault(cell_key(labels), []).append(
                (labels["le"], v))
        assert set(series) == set(sums) == set(counts), (
            f"{fam}: bucket/_sum/_count labelsets disagree")
        for key, bs in series.items():
            def le_val(le):
                return float("inf") if le == "+Inf" else float(le)
            bs = sorted(bs, key=lambda x: le_val(x[0]))
            cums = [v for _, v in bs]
            assert cums == sorted(cums), (
                f"{fam}{dict(key)}: non-monotone buckets {cums}")
            assert bs[-1][0] == "+Inf", f"{fam}{dict(key)}: missing +Inf"
            assert cums[-1] == counts[key], (
                f"{fam}{dict(key)}: +Inf bucket {cums[-1]} != _count "
                f"{counts[key]}")


class TestObservability:
    """The /debug/trace export and the engine's phase-attribution
    bookkeeping, exercised through real API traffic."""

    def test_debug_trace_perfetto_export(self, api_client):
        loop, client = api_client

        async def go():
            # Fresh traffic so the ring holds a complete lifecycle.
            r = await client.post("/v1/completions", json={
                "prompt": "trace me", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/debug/trace")
            assert r.status == 200
            return await r.json()
        doc = loop.run_until_complete(go())
        evs = doc["traceEvents"]
        assert isinstance(evs, list) and evs
        # Perfetto-loadable skeleton: process/thread metadata present.
        assert any(e.get("ph") == "M" for e in evs)
        # Request lifecycle spans: async begin/end pairs keyed by request id,
        # with the instant events (queued/scheduled/first_token) in between.
        reqs = [e for e in evs if e.get("cat") == "request"]
        opens = {e["id"] for e in reqs if e["ph"] == "b"}
        closes = {e["id"] for e in reqs if e["ph"] == "e"}
        assert opens and opens & closes, "no complete request span"
        names = {e["name"] for e in reqs if e["ph"] == "n"}
        assert {"queued", "scheduled", "first_token"} <= names
        # Step-phase attribution slices on the engine.step track.
        slices = [e for e in evs if e.get("ph") == "X"]
        assert {"schedule", "device_dispatch"} <= {s["name"] for s in slices}
        assert all(s["ts"] >= 0 and s["dur"] >= 0 for s in slices)
        json.dumps(doc)     # round-trips to the wire format

    def test_trace_clear_param(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.get("/debug/trace?clear=1")
            assert r.status == 200
            r2 = await client.get("/debug/trace")
            return await r2.json()
        doc = loop.run_until_complete(go())
        assert not [e for e in doc["traceEvents"]
                    if e.get("cat") == "request"]

    def test_phase_attribution_bookkeeping(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "phases", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
        loop.run_until_complete(go())
        obs = _SERVER["api"].engine.engine.obs
        assert obs.phases.steps_recorded > 0
        for phase in ("schedule", "host_prep", "device_dispatch",
                      "device_fetch", "postproc", "detokenize"):
            assert obs.phases.totals[phase] > 0.0, f"{phase} never recorded"
        b = obs.phases.breakdown()
        assert b["device_dispatch"]["count"] > 0
        assert b["device_dispatch"]["mean_ms"] >= 0
        # The TTFT decomposition bench.py folds into its JSON line.
        d = obs.ttft_decomposition()
        assert d["samples"] > 0
        assert all(k in d for k in ("queue_ms", "prefill_ms",
                                    "first_fetch_ms"))


class TestFleetTelemetry:
    """The device/SLO telemetry layer: new gauges present and nan-free on
    a scrape of the module's live server (exposition validity overall is
    pinned by _assert_valid_exposition in test_metrics_endpoint)."""

    def test_new_gauges_present_and_nan_free(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.get("/metrics")
            return await r.text()
        text = loop.run_until_complete(go())
        _assert_valid_exposition(text)

        def val(prefix):
            [line] = [l for l in text.splitlines() if l.startswith(prefix)]
            return float(line.rpartition(" ")[2])

        # HBM gauges: 0 on CPU (the backend reports nothing), never nan.
        assert val("kgct_hbm_bytes_limit ") >= 0
        assert val("kgct_hbm_bytes_in_use ") >= 0
        # jit-cache entry count: the module's traffic compiled something.
        assert val("kgct_jit_compiles_total ") > 0
        # Per-phase mean step time, promoted from the tracer's breakdown.
        assert "# TYPE kgct_step_phase_mean_seconds gauge" in text
        assert val('kgct_step_phase_mean_seconds{phase="device_dispatch"}'
                   ) > 0
        # Rolling SLO layer: attainment in [0, 1], budget > 0, goodput >= 0.
        att = val("kgct_slo_ttft_attainment_ratio ")
        assert 0.0 <= att <= 1.0
        assert val("kgct_slo_ttft_budget_ms ") > 0
        assert val("kgct_slo_goodput_tokens_per_sec ") >= 0

    def test_flightrecorder_endpoint(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.get("/debug/flightrecorder")
            assert r.status == 200
            return await r.json()
        doc = loop.run_until_complete(go())
        assert doc["enabled"] is True
        kinds = {e["kind"] for e in doc["events"]}
        # Mirrored lifecycle events from the module's traffic plus at
        # least one periodic state snapshot.
        assert "arrival" in kinds and "snapshot" in kinds
        snap = next(e for e in doc["events"] if e["kind"] == "snapshot")
        assert {"waiting", "running", "kv_pages_free"} <= set(snap)


class TestRequestIdPropagation:
    """The x-kgct-request-id contract on the replica side: an inbound id
    (the router's mint) becomes the ENGINE request id — shared with the
    lifecycle trace — and every response echoes an id, success or error."""

    def test_inbound_id_adopted_and_traced(self, api_client):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)
        loop, client = api_client
        rid = "req-test-correlate-1"

        async def go():
            r = await client.post(
                "/v1/completions",
                json={"prompt": "trace my id", "max_tokens": 4,
                      "temperature": 0.0},
                headers={REQUEST_ID_HEADER: rid})
            assert r.status == 200
            assert r.headers[REQUEST_ID_HEADER] == rid
            data = await r.json()
            assert data["id"] == rid              # engine adopted it
            rt = await client.get("/debug/trace")
            return await rt.json()
        doc = loop.run_until_complete(go())
        spans = [e for e in doc["traceEvents"]
                 if e.get("cat") == "request" and e.get("id") == rid]
        assert {e["ph"] for e in spans} >= {"b", "e"}, \
            "engine lifecycle trace does not carry the inbound id"

    def test_minted_id_on_success_and_errors(self, api_client):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)
        loop, client = api_client

        async def go():
            # No inbound header: a cmpl- id is minted and echoed.
            r = await client.post("/v1/completions", json={
                "prompt": "mint me", "max_tokens": 2, "temperature": 0.0})
            assert r.headers[REQUEST_ID_HEADER].startswith("cmpl-")
            assert (await r.json())["id"] == r.headers[REQUEST_ID_HEADER]
            # Error responses carry the id too (a 400 in a client log must
            # join the server's records).
            r400 = await client.post("/v1/completions",
                                     json={"max_tokens": 2})
            assert r400.status == 400
            assert REQUEST_ID_HEADER in r400.headers
            # An invalid inbound id (spaces) is ignored, not echoed.
            rbad = await client.post(
                "/v1/completions",
                json={"prompt": "x", "max_tokens": 2, "temperature": 0.0},
                headers={REQUEST_ID_HEADER: "bad id with spaces"})
            assert rbad.headers[REQUEST_ID_HEADER] != "bad id with spaces"
            # Streaming: the header rides the SSE response's headers.
            rs = await client.post("/v1/completions", json={
                "prompt": "s", "max_tokens": 2, "temperature": 0.0,
                "stream": True}, headers={REQUEST_ID_HEADER: "req-sse-7"})
            assert rs.headers[REQUEST_ID_HEADER] == "req-sse-7"
            await rs.read()
        loop.run_until_complete(go())

    def test_tracing_and_recorder_off_byte_identical(self, api_client):
        """The acceptance pin: tracer+recorder only OBSERVE — toggling both
        off must not perturb engine outputs (greedy, same warm engine)."""
        loop, client = api_client
        obs = _SERVER["api"].engine.engine.obs
        body = {"prompt": "identical under observation", "max_tokens": 6,
                "temperature": 0.0}

        async def one():
            r = await client.post("/v1/completions", json=body)
            assert r.status == 200
            return (await r.json())["choices"][0]["text"]
        text_on = loop.run_until_complete(one())
        obs.tracer.enabled = False
        obs.flight.enabled = False
        try:
            text_off = loop.run_until_complete(one())
        finally:
            obs.tracer.enabled = True
            obs.flight.enabled = True
        assert text_on == text_off


class TestRouter:
    def test_routes_and_failover(self, api_client):
        loop, client = api_client

        async def go():
            # Two "replicas": one real (the api server), one dead.
            real = f"http://{client.host}:{client.port}"
            router = Router([real, "http://127.0.0.1:1"],
                            health_interval_s=0.1)
            rclient = TestClient(TestServer(router.build_app()))
            await rclient.start_server()
            try:
                await asyncio.sleep(0.35)   # health loop marks dead replica
                r = await rclient.get("/health")
                body = await r.json()
                assert body["replicas"][real]["healthy"] is True
                assert body["replicas"]["http://127.0.0.1:1"]["healthy"] is False
                # Proxied completion end-to-end.
                r = await rclient.post("/v1/completions", json={
                    "prompt": "via router", "max_tokens": 4,
                    "temperature": 0.0})
                assert r.status == 200
                data = await r.json()
                assert data["choices"][0]["text"] is not None
                r = await rclient.get("/metrics")
                text = await r.text()
                assert "kgct_router_replica_healthy" in text
                _assert_valid_exposition(text)
            finally:
                await rclient.close()
        loop.run_until_complete(go())


class TestRouterFailover:
    def test_failover_to_next_replica_before_streaming(self, event_loop=None):
        """An upstream that refuses the connection is retried on the next
        healthy replica; the client sees a single successful response."""
        import asyncio
        import aiohttp
        from aiohttp import web as aioweb
        from kubernetes_gpu_cluster_tpu.serving.router import Router

        async def scenario():
            # live replica
            async def ok(request):
                return aioweb.json_response({"from": "live"})

            async def health(request):
                return aioweb.json_response({"status": "ok"})
            app = aioweb.Application()
            app.router.add_post("/v1/completions", ok)
            app.router.add_get("/health", health)
            runner = aioweb.AppRunner(app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = runner.addresses[0][1]

            # dead replica first in the list (connection refused)
            router = Router([f"http://127.0.0.1:1",      # nothing listens
                             f"http://127.0.0.1:{port}"],
                            health_interval_s=9999)
            rapp = router.build_app()
            rrunner = aioweb.AppRunner(rapp)
            await rrunner.setup()
            rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
            await rsite.start()
            rport = rrunner.addresses[0][1]
            # The startup probe already benched the dead replica; this test
            # is about the harder case — a replica that PASSED its probes and
            # died just before the request — so put it back in rotation.
            router.replicas[0].healthy = True
            router.replicas[0].consecutive_failures = 0
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.post(
                            f"http://127.0.0.1:{rport}/v1/completions",
                            json={"prompt": "x"}) as resp:
                        assert resp.status == 200
                        data = await resp.json()
                        assert data["from"] == "live"
            finally:
                await rrunner.cleanup()
                await runner.cleanup()

        asyncio.run(scenario())


class TestLogprobsAPI:
    def test_completions_logprobs(self, api_client):
        """OpenAI completions logprobs parity: logprobs: 1 returns the
        chosen-token logprobs aligned with tokens; >1 (alternatives) is a
        clean 400."""
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 4, "temperature": 0.0,
                "logprobs": 1})
            assert r.status == 200
            body = await r.json()
            lp = body["choices"][0]["logprobs"]
            assert len(lp["token_logprobs"]) == len(lp["tokens"]) == 4
            assert all(isinstance(x, float) and x <= 0.0
                       for x in lp["token_logprobs"])

            # Determinism: greedy rerun returns identical logprobs.
            r2 = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 4, "temperature": 0.0,
                "logprobs": 1})
            lp2 = (await r2.json())["choices"][0]["logprobs"]
            assert lp2["token_logprobs"] == lp["token_logprobs"]

            r3 = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 2, "logprobs": 5})
            assert r3.status == 200   # alternatives supported since r5
            assert "top_logprobs" in (await r3.json())["choices"][0]["logprobs"]

            # Off by default: no logprobs object.
            r4 = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 2, "temperature": 0.0})
            assert "logprobs" not in (await r4.json())["choices"][0]
        loop.run_until_complete(go())

    def test_streaming_logprobs_and_chat_rejection(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 4, "temperature": 0.0,
                "logprobs": 1, "stream": True})
            assert r.status == 200
            lps = []
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    ev = json.loads(line[len("data: "):])
                    lp = ev["choices"][0].get("logprobs")
                    if lp:
                        assert len(lp["tokens"]) == len(lp["token_logprobs"])
                        lps.extend(lp["token_logprobs"])
                if line == "data: [DONE]":
                    break
            assert len(lps) == 4 and all(x <= 0 for x in lps)

            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2, "logprobs": 1})
            assert r.status == 400
        loop.run_until_complete(go())


class TestSamplingTailAPI:
    """OpenAI sampling-surface tail (VERDICT r4 missing #3): presence/
    frequency penalties, per-request seed, echo — against the vLLM API the
    reference exposed (reference old_README.md:1472-1476)."""

    def test_echo_completions(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 3, "temperature": 0.0,
                "echo": True})
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["text"].startswith("hi")

            # echo + logprobs: prompt tokens present with null logprobs
            r2 = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 2, "temperature": 0.0,
                "echo": True, "logprobs": 1})
            lp = (await r2.json())["choices"][0]["logprobs"]
            assert len(lp["token_logprobs"]) == 3 + 2
            assert lp["token_logprobs"][:3] == [None, None, None]
            assert all(x <= 0 for x in lp["token_logprobs"][3:])

            # echo on chat is a clean 400
            r3 = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 2, "echo": True})
            assert r3.status == 400
        loop.run_until_complete(go())

    def test_echo_streaming_first_frame_is_prompt(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2, "temperature": 0.0,
                "echo": True, "stream": True})
            assert r.status == 200
            first = None
            # Drain to [DONE]: breaking early aborts the request server-side
            # and leaves a zombie window that changes the next test's batch.
            async for line in r.content:
                line = line.decode().strip()
                if line == "data: [DONE]":
                    break
                if line.startswith("data: ") and first is None:
                    first = json.loads(line[len("data: "):])
            assert first["choices"][0]["text"] == "hi"
        loop.run_until_complete(go())

    def test_seed_reproducible_over_api(self, api_client):
        loop, client = api_client

        async def go():
            req = {"prompt": [2, 8, 4], "max_tokens": 6, "temperature": 1.0,
                   "seed": 1234, "logprobs": 1}
            a = (await (await client.post("/v1/completions", json=req)).json())
            b = (await (await client.post("/v1/completions", json=req)).json())
            la = a["choices"][0]["logprobs"]["token_logprobs"]
            lb = b["choices"][0]["logprobs"]["token_logprobs"]
            assert la == lb
        loop.run_until_complete(go())

    def test_logprobs_alternatives_over_api(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [1, 5, 9], "max_tokens": 3, "temperature": 0.0,
                "logprobs": 3})
            assert r.status == 200
            lp = (await r.json())["choices"][0]["logprobs"]
            assert len(lp["top_logprobs"]) == 3
            for chosen_lp, tops in zip(lp["token_logprobs"],
                                       lp["top_logprobs"]):
                # OpenAI's dict-of-token-strings format collapses distinct
                # ids that decode identically (the byte tokenizer renders
                # out-of-range ids as "") — so <= 3 keys, not == 3; the
                # engine-level test asserts exact id-level counts.
                assert 1 <= len(tops) <= 3
                assert max(tops.values()) >= chosen_lp - 1e-5

            r2 = await client.post("/v1/completions", json={
                "prompt": [1, 5], "max_tokens": 2, "logprobs": 9})
            assert r2.status == 400

            # echo + alternatives: prompt positions are null
            r3 = await client.post("/v1/completions", json={
                "prompt": [1, 5], "max_tokens": 2, "temperature": 0.0,
                "logprobs": 2, "echo": True})
            lp3 = (await r3.json())["choices"][0]["logprobs"]
            assert lp3["top_logprobs"][:2] == [None, None]
            assert len(lp3["top_logprobs"]) == 4
        loop.run_until_complete(go())

    def test_logit_bias_and_best_of(self, api_client):
        loop, client = api_client

        async def go():
            # logit_bias forces the token end-to-end over the API
            r = await client.post("/v1/completions", json={
                "prompt": [3, 1], "max_tokens": 3, "temperature": 0.0,
                "logit_bias": {"70": 100}, "logprobs": 1})
            assert r.status == 200
            # token id 70 maps to byte 'C' in the byte tokenizer (70-3=67)
            body = await r.json()
            assert body["choices"][0]["text"] == "CCC"

            r2 = await client.post("/v1/completions", json={
                "prompt": [3, 1], "max_tokens": 2, "logit_bias": {"5": 200}})
            assert r2.status == 400

            # best_of: 3 candidates, top-1 by mean logprob returned
            r3 = await client.post("/v1/completions", json={
                "prompt": [2, 8], "max_tokens": 4, "temperature": 1.0,
                "seed": 9, "best_of": 3})
            assert r3.status == 200
            assert len((await r3.json())["choices"]) == 1

            r4 = await client.post("/v1/completions", json={
                "prompt": [2, 8], "max_tokens": 2, "n": 3, "best_of": 2})
            assert r4.status == 400
        loop.run_until_complete(go())

    def test_penalties_accepted_and_validated(self, api_client):
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [3, 1], "max_tokens": 4, "temperature": 0.5,
                "presence_penalty": 1.0, "frequency_penalty": 0.5})
            assert r.status == 200
            assert len((await r.json())["choices"]) == 1

            r2 = await client.post("/v1/completions", json={
                "prompt": [3, 1], "max_tokens": 2, "presence_penalty": 9.0})
            assert r2.status == 400
            msg = (await r2.json())["error"]["message"]
            assert "presence_penalty" in msg
        loop.run_until_complete(go())


class TestClientDisconnectAborts:
    """A client that goes away must not leave device work running: every
    handler exit path calls engine.abort (previously asserted only by
    comments). Requests here ask for FAR more tokens than the poll deadline
    allows, so a missing abort fails the test instead of passing slowly."""

    async def _wait_engine_idle(self, eng, deadline_s=8.0):
        deadline = time.monotonic() + deadline_s
        while eng.has_unfinished_requests():
            assert time.monotonic() < deadline, (
                "engine still has unfinished requests after client "
                "disconnect — abort path leaked device work")
            await asyncio.sleep(0.02)

    def test_streaming_disconnect_aborts_engine_request(self, api_client):
        loop, client = api_client

        async def go():
            eng = _SERVER["api"].engine.engine
            r = await client.post("/v1/completions", json={
                "prompt": "run forever", "max_tokens": 400,
                "temperature": 0.0, "stream": True})
            assert r.status == 200
            async for line in r.content:
                if line.decode().strip().startswith("data: "):
                    break       # first token delivered: request is live
            assert eng.has_unfinished_requests()
            r.close()           # client vanishes mid-stream
            await self._wait_engine_idle(eng)
            # The server survives and keeps serving.
            r2 = await client.post("/v1/completions", json={
                "prompt": "still alive", "max_tokens": 4,
                "temperature": 0.0})
            assert r2.status == 200
        loop.run_until_complete(go())

    def test_n_gt_1_disconnect_aborts_all_subrequests(self, api_client):
        loop, client = api_client

        async def go():
            eng = _SERVER["api"].engine.engine
            with pytest.raises(asyncio.TimeoutError):
                await client.post("/v1/completions", json={
                    "prompt": [2, 8, 4], "max_tokens": 400,
                    "temperature": 1.0, "seed": 3, "n": 2},
                    timeout=aiohttp.ClientTimeout(total=0.5))
            await self._wait_engine_idle(eng)
        loop.run_until_complete(go())

    def test_best_of_disconnect_aborts_all_candidates(self, api_client):
        loop, client = api_client

        async def go():
            eng = _SERVER["api"].engine.engine
            with pytest.raises(asyncio.TimeoutError):
                await client.post("/v1/completions", json={
                    "prompt": [2, 8], "max_tokens": 400,
                    "temperature": 1.0, "seed": 7, "best_of": 3},
                    timeout=aiohttp.ClientTimeout(total=0.5))
            await self._wait_engine_idle(eng)
            r = await client.post("/v1/completions", json={
                "prompt": [2, 8], "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
        loop.run_until_complete(go())


class TestSessionAffinityPassthrough:
    def test_session_id_and_user_accepted_and_validated(self, api_client):
        """The prefix-affinity router's stickiness keys pass through the
        engine: scalar session_id/user are accepted (and otherwise
        ignored); non-scalar values are a loud 400 — they would silently
        change the ROUTER's per-request hashing semantics."""
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2, "temperature": 0.0,
                "session_id": "conv-1", "user": "u-9"})
            assert r.status == 200
            r2 = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2,
                "session_id": {"nested": "object"}})
            assert r2.status == 400
            assert "session_id" in (await r2.json())["error"]["message"]
            r3 = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2, "user": ["a", "b"]})
            assert r3.status == 400
        loop.run_until_complete(go())


class TestMultipleCompletions:
    def test_n_choices(self, api_client):
        """OpenAI n > 1: n concurrent engine requests gathered into indexed
        choices; greedy n=2 must produce identical texts (deterministic)."""
        loop, client = api_client

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": [2, 8, 4], "max_tokens": 4, "temperature": 0.0,
                "n": 2})
            assert r.status == 200
            body = await r.json()
            assert [c["index"] for c in body["choices"]] == [0, 1]
            assert body["choices"][0]["text"] == body["choices"][1]["text"]
            assert body["usage"]["completion_tokens"] == 8

            r = await client.post("/v1/completions", json={
                "prompt": [2, 8], "max_tokens": 2, "n": 2, "stream": True})
            assert r.status == 400
            r = await client.post("/v1/completions", json={
                "prompt": [2, 8], "max_tokens": 2, "n": 0})
            assert r.status == 400
        loop.run_until_complete(go())


class TestKVHandoffOnWarmServer:
    """Disaggregated-serving paths that need only the module's warm
    role="both" server: the export endpoint, and the decode-side fallback
    to local recompute (chaos site kv_handoff_fail + dead prefill URL),
    with the flight recorder capturing the fallback trigger."""

    def test_kv_handoff_export_endpoint(self, api_client):
        from kubernetes_gpu_cluster_tpu.serving.handoff import decode_handoff

        loop, client = api_client

        async def go():
            r = await client.post("/internal/kv_handoff", json={
                "prompt_token_ids": list(range(2, 40)),
                "temperature": 0.0})
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/octet-stream"
            state = decode_handoff(await r.read())
            assert state["model"] == "debug-tiny"
            assert len(state["output_token_ids"]) == 1   # max_tokens clamp
            assert state["k"].shape[1] > 0
            # Malformed bodies are loud 400s, not engine crashes.
            r = await client.post("/internal/kv_handoff",
                                  json={"prompt_token_ids": []})
            assert r.status == 400
            r = await client.post("/internal/kv_handoff",
                                  json={"prompt_token_ids": ["x"]})
            assert r.status == 400
        loop.run_until_complete(go())

    def test_export_failure_counts_outcome_error(self, api_client):
        """An export that dies AFTER admission (engine-side rejection —
        here an out-of-vocab logit_bias id surfacing through the worker)
        must move kgct_disagg_handoffs_total{side="export",
        outcome="error"}: an operator watching a failing prefill pool
        reads the counter, while the 400 itself only reaches the one
        client (the decode side can only ever count its own fallbacks)."""
        loop, client = api_client
        server = _SERVER["api"]

        async def go():
            before = server.disagg.handoffs.get(("export", "error"), 0)
            r = await client.post("/internal/kv_handoff", json={
                "prompt_token_ids": list(range(2, 10)),
                "temperature": 0.0,
                "logit_bias": {"999999": 5}})
            assert r.status == 400
            assert server.disagg.handoffs[("export", "error")] == before + 1
        loop.run_until_complete(go())

    def test_handoff_pull_failure_falls_back_to_local_recompute(
            self, api_client):
        """A completion carrying a prefill-url header whose pull fails —
        chaos-injected (kv_handoff_fail) or a dead upstream — serves the
        SAME output as a plain request (local recompute), 200, with the
        fallback trigger captured in trace ring + flight recorder and the
        fallback counter on /metrics."""
        from kubernetes_gpu_cluster_tpu.resilience.faults import (
            configure_faults)
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFILL_URL_HEADER)

        loop, client = api_client
        body = {"prompt": "fall back please", "max_tokens": 4,
                "temperature": 0.0}

        async def go():
            r = await client.post("/v1/completions", json=body)
            ref = (await r.json())["choices"][0]["text"]

            configure_faults("kv_handoff_fail")
            try:
                r = await client.post(
                    "/v1/completions", json=body,
                    headers={PREFILL_URL_HEADER: "http://127.0.0.1:9"})
                assert r.status == 200
                assert (await r.json())["choices"][0]["text"] == ref
            finally:
                configure_faults(None)
            # Unarmed but dead upstream: the bounded fetch fails, same
            # graceful fallback.
            r = await client.post(
                "/v1/completions", json=body,
                headers={PREFILL_URL_HEADER: "http://127.0.0.1:9"})
            assert r.status == 200
            assert (await r.json())["choices"][0]["text"] == ref

            flight = _SERVER["api"].engine.engine.obs.flight.export()
            falls = [e for e in flight["events"]
                     if e["kind"] == "handoff"
                     and e.get("outcome") == "fallback"]
            assert len(falls) >= 2       # chaos trigger + dead upstream
            assert any("kv_handoff_fail" in (e.get("error") or "")
                       for e in falls)
            r = await client.get("/metrics")
            text = await r.text()
            _assert_valid_exposition(text)
            assert ('kgct_disagg_handoffs_total{side="import",'
                    'outcome="fallback"} 2') in text
            assert 'kgct_engine_role{role="both"} 1' in text
        loop.run_until_complete(go())

    def test_prefill_pool_allowlist_gates_the_pull(self, api_client):
        """With --prefill-pool set, a header naming an out-of-pool URL is
        NEVER fetched (SSRF guard for direct-to-pod traffic) — the request
        serves by local recompute with the allowlist rejection, not a
        connect error, as the fallback trigger; an in-pool URL still
        reaches the fetch path."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            PREFILL_URL_HEADER)

        loop, client = api_client
        server = _SERVER["api"]
        body = {"prompt": "allowlist me", "max_tokens": 4,
                "temperature": 0.0}
        assert server.prefill_pool is None   # warm server: trust-the-net
        server.prefill_pool = frozenset({"http://127.0.0.1:9"})
        try:

            async def go():
                r = await client.post("/v1/completions", json=body)
                ref = (await r.json())["choices"][0]["text"]
                r = await client.post(
                    "/v1/completions", json=body,
                    headers={PREFILL_URL_HEADER: "http://evil.example:80"})
                assert r.status == 200
                assert (await r.json())["choices"][0]["text"] == ref
                flight = server.engine.engine.obs.flight.export()
                rejects = [e for e in flight["events"]
                           if e["kind"] == "handoff"
                           and "not in --prefill-pool"
                           in (e.get("error") or "")]
                assert len(rejects) == 1
                # In-pool URL (trailing slash tolerated) passes the gate:
                # the pull itself then fails on the dead upstream — a
                # CONNECT error, not the allowlist.
                r = await client.post(
                    "/v1/completions", json=body,
                    headers={PREFILL_URL_HEADER: "http://127.0.0.1:9/"})
                assert r.status == 200
                assert (await r.json())["choices"][0]["text"] == ref
                flight = server.engine.engine.obs.flight.export()
                rejects = [e for e in flight["events"]
                           if e["kind"] == "handoff"
                           and "not in --prefill-pool"
                           in (e.get("error") or "")]
                assert len(rejects) == 1   # unchanged
            loop.run_until_complete(go())
        finally:
            server.prefill_pool = None

    def test_engine_side_import_fallback_reports_to_metrics(self, api_client):
        """An ENGINE-side import failure (worker thread, after the pull was
        already counted ok) reports through the on_import_fallback hook the
        server installs — without it /metrics reads 100% successful imports
        on a replica that recomputes everything."""
        loop, client = api_client
        server = _SERVER["api"]
        assert server.engine.on_import_fallback is not None
        before = server.disagg.handoffs.get(("import", "fallback"), 0)
        server.engine.on_import_fallback()
        assert server.disagg.handoffs[("import", "fallback")] == before + 1


class TestWorkerOpShutdownGuard:
    """An op enqueued after the worker thread's final wakeup can never
    drain — run_in_worker must fail the awaiter NOW (a kv_handoff export
    would otherwise hang until the client's own timeout) and
    post_to_worker must drop loudly instead of enqueueing into the void.
    Engine-free: the guard reads only the op-queue fields."""

    def _dead_engine(self):
        import threading

        from kubernetes_gpu_cluster_tpu.serving.async_engine import (
            AsyncLLMEngine)
        eng = AsyncLLMEngine.__new__(AsyncLLMEngine)
        eng._cv = threading.Condition()
        eng._ops = []
        eng._shutdown = True
        eng._thread = threading.Thread()   # never started
        return eng

    def test_run_in_worker_fails_fast_after_shutdown(self):
        eng = self._dead_engine()

        async def go():
            with pytest.raises(RuntimeError, match="shut down"):
                await eng.run_in_worker(lambda e: 1)
        asyncio.run(go())
        assert eng._ops == []            # never enqueued

    def test_post_to_worker_drops_after_shutdown(self):
        eng = self._dead_engine()
        eng.post_to_worker(lambda e: 1)
        assert eng._ops == []
