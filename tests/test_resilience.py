"""Unit coverage for the resilience package: KGCT_FAULT grammar and
determinism, admission-control estimates and shedding, the step watchdog
state machine, drain transitions, loop liveness, and the histogram quantile
the admission controller reads. Pure host-side logic — no engine, no jax."""

import asyncio
import math
import time

import pytest

from kubernetes_gpu_cluster_tpu.observability.prometheus import Histogram
from kubernetes_gpu_cluster_tpu.resilience import (AdmissionController,
                                                   DrainState, FaultInjector,
                                                   LoopLiveness,
                                                   ResilienceHub,
                                                   StepWatchdog,
                                                   configure_faults, inject)
from kubernetes_gpu_cluster_tpu.resilience.drain import (DRAINED, DRAINING,
                                                         SERVING,
                                                         drain_and_notify)
from kubernetes_gpu_cluster_tpu.resilience.faults import fault_value


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    configure_faults(None)


class TestFaultGrammar:
    def test_multi_rule_spec(self):
        inj = FaultInjector("replica_hang:p=1;step_stall:after=10,delay=0.5")
        assert set(inj.rules) == {"replica_hang", "step_stall"}
        assert inj.rules["step_stall"].after == 10
        assert inj.rules["step_stall"].delay == 0.5

    def test_bad_param_rejected(self):
        with pytest.raises(ValueError, match="bad param"):
            FaultInjector("step_stall:bogus=1")
        with pytest.raises(ValueError, match="empty site"):
            FaultInjector(":p=1")
        with pytest.raises(ValueError, match="outside"):
            FaultInjector("x:p=2")
        with pytest.raises(ValueError, match="duplicate"):
            FaultInjector("x:p=1;x:p=1")

    def test_after_and_times(self):
        inj = FaultInjector("site:after=2,times=2")
        rule = inj.rules["site"]
        fires = [rule.should_fire() for _ in range(6)]
        # Skips the first 2 checks, fires exactly twice, then exhausted.
        assert fires == [False, False, True, True, False, False]

    def test_probability_deterministic_per_seed(self):
        a = FaultInjector("s:p=0.5,seed=7").rules["s"]
        b = FaultInjector("s:p=0.5,seed=7").rules["s"]
        seq_a = [a.should_fire() for _ in range(32)]
        seq_b = [b.should_fire() for _ in range(32)]
        assert seq_a == seq_b                      # same seed, same sequence
        assert any(seq_a) and not all(seq_a)       # actually probabilistic

    def test_inject_unarmed_is_free(self):
        configure_faults(None)
        assert inject("anything") is False
        assert fault_value("anything") is None

    def test_configure_and_value(self):
        configure_faults("queue_wait_est:value=12.5")
        assert fault_value("queue_wait_est") == 12.5
        configure_faults(None)
        assert fault_value("queue_wait_est") is None


class _FakeObs:
    def __init__(self):
        self.queue_wait = Histogram("kgct_queue_wait_seconds")
        self.step_duration = Histogram("kgct_step_seconds")


class _FakeScheduler:
    def __init__(self, depth=0):
        self.waiting = [object()] * depth


class _FakeEngine:
    def __init__(self, depth=0):
        self.obs = _FakeObs()
        self.scheduler = _FakeScheduler(depth)


class TestAdmissionController:
    def test_no_budget_admits_everything(self):
        adm = AdmissionController(_FakeEngine(depth=100))
        assert adm.check(None) is None
        assert adm.shed_total == 0

    def test_empty_queue_estimates_zero(self):
        eng = _FakeEngine(depth=0)
        eng.obs.queue_wait.observe(30.0)    # history says "slow"...
        adm = AdmissionController(eng, default_budget_ms=100)
        # ...but nothing is queued now: the next schedule admits immediately.
        assert adm.estimate_queue_wait_s() == 0.0
        assert adm.check(None) is None

    def test_sheds_when_history_blows_budget(self):
        eng = _FakeEngine(depth=4)
        for _ in range(10):
            eng.obs.queue_wait.observe(8.0)
        adm = AdmissionController(eng, default_budget_ms=1000)
        retry = adm.check(None)
        assert retry is not None
        assert 1 <= retry <= 60
        assert adm.shed_total == 1
        # An explicit generous budget is admitted.
        assert adm.check(60_000) is None

    def test_depth_term_leads_lagging_histogram(self):
        eng = _FakeEngine(depth=50)
        for _ in range(10):
            eng.obs.step_duration.observe(0.2)   # 50 deep x 0.2 s/step = 10 s
        adm = AdmissionController(eng, default_budget_ms=2000)
        assert adm.check(None) is not None
        assert adm.last_estimate_s >= 5.0

    def test_fault_forced_estimate(self):
        configure_faults("queue_wait_est:value=30")
        adm = AdmissionController(_FakeEngine(depth=0),
                                  default_budget_ms=1000)
        retry = adm.check(None)
        assert retry == 30
        assert adm.last_estimate_s == 30.0

    def test_windowed_quantile_forgets_old_overload(self):
        """A past overload episode must stop inflating the estimate once it
        leaves the sliding window — the lifetime histogram never decays, so
        the controller differences bucket counts against a rotating
        snapshot (and a recovered server stops shedding)."""
        eng = _FakeEngine(depth=2)
        for _ in range(50):
            eng.obs.queue_wait.observe(8.0)      # the bad old days
        adm = AdmissionController(eng, default_budget_ms=1000,
                                  window_s=0.01)
        assert adm.check(None) is not None       # history in first window
        # Rotate past the episode: two rotations age it out entirely.
        time.sleep(0.02)
        adm.estimate_queue_wait_s()
        time.sleep(0.02)
        adm.estimate_queue_wait_s()
        # Fresh window holds only fast waits now.
        eng.obs.queue_wait.observe(0.01)
        assert adm.check(None) is None
        # New slow observations inside the current window count again.
        for _ in range(50):
            eng.obs.queue_wait.observe(8.0)
        assert adm.check(None) is not None


class TestHistogramQuantile:
    def test_empty_is_zero(self):
        assert Histogram("h").quantile(0.9) == 0.0

    def test_interpolates_within_bucket(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.5)      # all in the (1, 2] bucket
        q = h.quantile(0.5)
        assert 1.0 < q <= 2.0

    def test_merges_labelsets_and_clamps_tail(self):
        h = Histogram("h", buckets=(1.0, 2.0), labels=("outcome",))
        h.observe(0.5, ("finished",))
        h.observe(100.0, ("aborted",))     # above last finite bound
        assert h.quantile(0.99) == 2.0     # clamps to last finite bucket
        assert h.count == 2 and h.sum == pytest.approx(100.5)

    def test_monotone_in_q(self):
        h = Histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        qs = [h.quantile(q) for q in (0.1, 0.5, 0.9)]
        assert qs == sorted(qs)


class TestStepWatchdog:
    def test_trip_and_recover(self):
        trips = []
        wd = StepWatchdog(timeout_s=0.01, on_trip=lambda: trips.append(1))
        wd.arm()
        time.sleep(0.03)
        assert wd._check_once() is True
        assert not wd.healthy and wd.trips == 1 and trips == [1]
        # Same hung step does not double-count.
        assert wd._check_once() is False
        assert wd.trips == 1
        # The step finally completes: health recovers.
        wd.disarm()
        assert wd.healthy

    def test_no_trip_when_disarmed_or_fast(self):
        wd = StepWatchdog(timeout_s=0.05)
        assert wd._check_once() is False        # never armed
        wd.arm()
        assert wd._check_once() is False        # within deadline
        wd.disarm()
        assert wd.healthy and wd.trips == 0

    def test_watcher_thread_lifecycle(self):
        wd = StepWatchdog(timeout_s=0.02)
        wd.start()
        wd.start()      # idempotent
        wd.arm()
        deadline = time.monotonic() + 1.0
        while wd.healthy and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not wd.healthy and wd.trips >= 1
        wd.disarm()
        wd.stop()


class TestDrain:
    def test_state_machine(self):
        d = DrainState()
        assert d.state == SERVING and d.gauge_value == 0
        assert not d.is_draining
        assert d.start_drain() is True
        assert d.start_drain() is False          # idempotent under SIGTERM x2
        assert d.state == DRAINING and d.gauge_value == 1 and d.is_draining
        d.mark_drained()
        assert d.state == DRAINED and d.gauge_value == 2

    def test_mark_drained_requires_draining(self):
        d = DrainState()
        d.mark_drained()
        assert d.state == SERVING    # no-op outside a drain

    def test_drain_and_notify_waits_for_idle(self):
        class _Eng:
            def __init__(self):
                self.calls = 0

            def has_unfinished_requests(self):
                self.calls += 1
                return self.calls < 3     # busy twice, then idle

        class _Async:
            def __init__(self):
                self.engine = _Eng()

        d = DrainState()
        d.start_drain()
        fired = []
        asyncio.run(drain_and_notify(d, _Async(), grace_s=5.0,
                                     on_drained=lambda: fired.append(1),
                                     poll_s=0.01))
        assert d.state == DRAINED and fired == [1]

    def test_drain_grace_lapses(self):
        class _Async:
            class engine:            # noqa: N801 - attribute shim
                @staticmethod
                def has_unfinished_requests():
                    return True      # never goes idle

        d = DrainState()
        d.start_drain()
        t0 = time.monotonic()
        asyncio.run(drain_and_notify(d, _Async(), grace_s=0.05, poll_s=0.01))
        assert d.state == DRAINED
        assert time.monotonic() - t0 < 1.0


class TestLoopLiveness:
    def test_starting_state_is_alive_indefinitely(self):
        # Before the first beat the loop is STARTING (a follower waits for
        # the leader's lazy connect, possibly minutes): never report dead.
        lv = LoopLiveness(timeout_s=0.05)
        time.sleep(0.08)
        assert lv.alive() and lv.reason == ""

    def test_beats_and_timeout(self):
        lv = LoopLiveness(timeout_s=0.05)
        lv.beat()
        assert lv.alive() and lv.reason == ""
        time.sleep(0.08)
        assert not lv.alive()
        assert "no heartbeat" in lv.reason
        lv.beat()
        assert lv.alive()

    def test_mark_dead_is_terminal(self):
        lv = LoopLiveness(timeout_s=10)
        lv.mark_dead("leader gone")
        assert not lv.alive() and lv.reason == "leader gone"
        lv.beat()
        assert not lv.alive()       # dead is dead until restart


class TestResilienceHub:
    def test_prometheus_lines(self):
        adm = AdmissionController(_FakeEngine())
        adm.shed_total = 3
        wd = StepWatchdog()
        wd.trips = 2
        drain = DrainState()
        drain.start_drain()
        lines = ResilienceHub(adm, wd, drain).render_prometheus()
        text = "\n".join(lines)
        assert "kgct_requests_shed_total 3" in text
        assert "kgct_watchdog_trips_total 2" in text
        assert "kgct_drain_state 1" in text
        # Every sample is a finite number (scrape-clean).
        for line in lines:
            if not line.startswith("#"):
                assert math.isfinite(float(line.rsplit(" ", 1)[1]))
