"""Session survivability: live KV migration + transparent mid-stream failover.

Tier-1 keeps the CHEAP pins:

- the acceptance contract at the engine seam — a sequence exported
  MID-DECODE (``export_running``) and imported on a SECOND engine produces
  the same remaining tokens/logprobs as the uninterrupted run, for greedy
  AND seeded sampling with penalties — plus the token-replay
  (``resume_outputs``) recompute rung, byte-identical the same way;
- engine-free pins of the parking lot (MigrationStore bounds), the
  router's SSE relay parser (token-ledger strip), and the router failover
  ladder over stub replicas (``replica_kill_midstream`` chaos ->
  transparent splice; exhausted ladder -> clean truncated-stream error).

The real multi-engine topology (drain migration and kill-mid-stream
failover with actual engines behind the router) is @slow, per the tier-1
budget guard. The drain-path chaos pins that reuse the warm module server
live in tests/test_chaos.py.
"""

import asyncio
import json

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.errors import (
    MIGRATE_URL_HEADER, REQUEST_ID_HEADER, RESUME_MODE_HEADER)
from kubernetes_gpu_cluster_tpu.serving.handoff import (
    MigrationStore, decode_handoff, encode_handoff)


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


def _engine_config(**sched_kw):
    kw = dict(max_num_seqs=4, max_prefill_tokens=64,
              decode_buckets=(1, 2), prefill_buckets=(64,),
              decode_window=4, mixed_batch_enabled=False)
    kw.update(sched_kw)
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(**kw))


@pytest.fixture(scope="module")
def engines():
    """(exporter, importer): two distinct engines with identical weights by
    construction — the acceptance criterion's 'second engine'. The state
    still crosses the full gather -> host buffer -> wire -> scatter path,
    which is exactly what distinct replicas exchange."""
    return LLMEngine(_engine_config()), LLMEngine(_engine_config())


PROMPT = np.random.default_rng(7).integers(1, 500, 40).tolist()


def _run_to_completion(eng, rid):
    final = None
    while eng.has_unfinished_requests():
        for o in eng.step():
            if o.request_id == rid and o.finished:
                final = o
    return final


def _step_until_outputs(eng, rid, n):
    """Step until the RUNNING sequence has committed >= n output tokens
    (mid-decode by construction: neither finished nor still prefilling)."""
    while True:
        seq = eng.scheduler.find_running(rid)
        if seq is not None and len(seq.output_token_ids) >= n:
            return seq
        assert eng.has_unfinished_requests(), \
            f"{rid} finished before reaching {n} outputs"
        eng.step()


def _drain_engine(eng):
    """Drain any in-flight window chain (deferred page releases happen at
    chain-drain time) so per-test page accounting is exact."""
    while eng.has_unfinished_requests():
        eng.step()


class TestMidStreamByteIdentity:
    """The acceptance pin: export mid-decode on engine 1, import on engine
    2, and the spliced run is byte-identical to the uninterrupted one."""

    def _roundtrip(self, engines, rid, params, split=4):
        e1, e2 = engines
        ref = e1.generate([PROMPT], params)[0]
        free1 = e1.scheduler.allocator.num_free
        free2 = e2.scheduler.allocator.num_free
        e1.add_request(f"{rid}-src", PROMPT, params)
        _step_until_outputs(e1, f"{rid}-src", split)
        state = e1.export_running(f"{rid}-src")
        assert state["mid_stream"] is True
        # The export is the committed history only — never the full run.
        assert len(state["output_token_ids"]) < len(ref.output_token_ids)
        assert ref.output_token_ids[:len(state["output_token_ids"])] == \
            state["output_token_ids"]
        # The sampling snapshot survives the wire (forensic + re-dispatch).
        rt = SamplingParams.from_state(state["sampling"])
        assert rt.seed == params.seed and rt.max_tokens == params.max_tokens
        state = decode_handoff(encode_handoff(state))   # actual wire bytes
        outs = e2.import_request(f"{rid}-dst", PROMPT, params, state)
        assert outs[0].new_token_ids == state["output_token_ids"]
        final = (_run_to_completion(e2, f"{rid}-dst")
                 if not outs[0].finished else outs[0])
        _drain_engine(e1)   # zombie chain: deferred page release
        assert e1.scheduler.allocator.num_free == free1, "exporter leaked"
        assert e2.scheduler.allocator.num_free == free2, "importer leaked"
        return ref, final

    def test_greedy_midstream_identical_to_uninterrupted(self, engines):
        params = SamplingParams(max_tokens=12, temperature=0.0,
                                logprobs=True)
        ref, got = self._roundtrip(engines, "g", params)
        assert got.output_token_ids == ref.output_token_ids
        np.testing.assert_allclose(got.output_logprobs, ref.output_logprobs,
                                   rtol=1e-5, atol=1e-5)
        assert got.finish_reason == ref.finish_reason

    def test_seeded_sampled_with_penalties_identical(self, engines):
        """Seeded sampling + presence/frequency penalties: the penalties
        read the output history the export carries, and the sample keys
        derive from (seed, position) — both engine-independent, so the
        migrated continuation cannot fork."""
        params = SamplingParams(max_tokens=12, temperature=0.9, top_k=30,
                                top_p=0.95, seed=17, presence_penalty=0.4,
                                frequency_penalty=0.3, logprobs=True)
        ref, got = self._roundtrip(engines, "s", params, split=5)
        assert got.output_token_ids == ref.output_token_ids
        np.testing.assert_allclose(got.output_logprobs, ref.output_logprobs,
                                   rtol=1e-5, atol=1e-5)

    def test_token_replay_resume_identical(self, engines):
        """The recompute rung (no migrated KV): already-relayed tokens are
        pre-seeded as OUTPUT history and admission replays prompt+outputs
        through the recompute-prefill path — same byte-identity contract,
        greedy and seeded."""
        e1, e2 = engines
        for tag, params in (
                ("rp-g", SamplingParams(max_tokens=10, temperature=0.0)),
                ("rp-s", SamplingParams(max_tokens=10, temperature=0.8,
                                        top_k=40, seed=23,
                                        presence_penalty=0.5))):
            ref = e1.generate([PROMPT], params)[0]
            e2.add_request(tag, PROMPT, params,
                           resume_outputs=ref.output_token_ids[:4])
            final = _run_to_completion(e2, tag)
            assert final.output_token_ids == ref.output_token_ids, tag

    def test_resume_history_already_stopped_rejected(self, engines):
        """A replay that already satisfies a stop condition has nothing
        left to generate — loud ValueError, not a hung entry."""
        e1, e2 = engines
        params = SamplingParams(max_tokens=4, temperature=0.0)
        ref = e1.generate([PROMPT], params)[0]
        with pytest.raises(ValueError, match="nothing to resume"):
            e2.add_request("rp-done", PROMPT, params,
                           resume_outputs=ref.output_token_ids)
        assert e2.scheduler.find_running("rp-done") is None
        _drain_engine(e2)

    def test_export_running_requires_a_running_sequence(self, engines):
        e1, _ = engines
        with pytest.raises(KeyError):
            e1.export_running("never-seen")
        # A WAITING sequence has no committed device pages worth shipping:
        # the drain's wait-it-out rung owns it, not the migration seam.
        e1.add_request("wt", PROMPT, SamplingParams(max_tokens=2,
                                                    temperature=0.0))
        try:
            with pytest.raises(KeyError):
                e1.export_running("wt")
        finally:
            _drain_engine(e1)

    def test_migrated_outcome_splits_out_in_observability(self, engines):
        """FinishReason.MIGRATE is locally terminal without a client-facing
        finish: the e2e outcome series labels it 'migrated' (the tokens
        WERE delivered — the goodput gate keeps them, and dashboards can
        split migrated finishes from real ones)."""
        e1, _ = engines
        params = SamplingParams(max_tokens=12, temperature=0.0)
        cell0 = e1.obs.e2e_latency._cells.get(("migrated",))
        n0 = cell0[2] if cell0 else 0
        e1.add_request("obs", PROMPT, params)
        _step_until_outputs(e1, "obs", 4)
        e1.export_running("obs")
        _drain_engine(e1)
        assert e1.obs.e2e_latency._cells[("migrated",)][2] == n0 + 1


class TestMigrationStore:
    """Engine-free bounds of the parking lot: a crashing fleet cannot
    balloon a healthy replica's host memory."""

    def test_cap_evicts_oldest(self):
        store = MigrationStore(cap=3, ttl_s=60.0)
        for i in range(5):
            store.put(f"r{i}", {"i": i})
        assert len(store) == 3
        assert store.pop("r0") is None and store.pop("r1") is None
        assert store.pop("r4") == {"i": 4}

    def test_ttl_expires(self):
        now = [0.0]
        store = MigrationStore(cap=4, ttl_s=10.0, clock=lambda: now[0])
        store.put("a", {"x": 1})
        now[0] = 5.0
        store.put("b", {"x": 2})
        now[0] = 10.5    # a's deadline (10.0) passed; b's (15.0) has not
        assert store.pop("a") is None
        assert store.pop("b") == {"x": 2}

    def test_repush_replaces_and_pop_consumes(self):
        store = MigrationStore(cap=2, ttl_s=60.0)
        store.put("a", {"v": 1})
        store.put("a", {"v": 2})
        assert len(store) == 1
        assert store.pop("a") == {"v": 2}
        assert store.pop("a") is None


class TestSSERelay:
    """Engine-free pins of the router's parse-mode relay: the embedded
    token ledger is kept (and stripped before the client), partial frames
    never leak, and non-ledger frames pass through byte-identical."""

    def _frame(self, text, toks=None, **extra):
        obj = {"choices": [{"text": text}], **extra}
        if toks is not None:
            obj["kgct_token_ids"] = toks
        return b"data: " + json.dumps(obj).encode() + b"\n\n"

    def test_ledger_kept_and_stripped(self):
        from kubernetes_gpu_cluster_tpu.serving.router import _SSERelay
        relay = _SSERelay()
        out = relay.feed(self._frame("a", [1, 2]) + self._frame("b", [3]))
        assert relay.tokens == [1, 2, 3]
        assert b"kgct_token_ids" not in out
        assert b'"text": "a"' in out and b'"text": "b"' in out
        assert not relay.done
        out = relay.feed(b"data: [DONE]\n\n")
        assert relay.done and b"[DONE]" in out

    def test_partial_frame_buffered_and_resettable(self):
        from kubernetes_gpu_cluster_tpu.serving.router import _SSERelay
        relay = _SSERelay()
        whole = self._frame("a", [5])
        assert relay.feed(whole[:10]) == b""
        # Upstream dies here: the partial frame must never reach the
        # client, and the ledger covers only fully-relayed frames.
        relay.reset_buffer()
        assert relay.tokens == []
        out = relay.feed(self._frame("a", [5]))
        assert relay.tokens == [5] and b'"text": "a"' in out

    def test_frames_without_ledger_pass_through_byte_identical(self):
        from kubernetes_gpu_cluster_tpu.serving.router import _SSERelay
        relay = _SSERelay()
        plain = self._frame("x")
        assert relay.feed(plain) == plain
        weird = b"data: not json\n\n"
        assert relay.feed(weird) == weird
        assert relay.tokens == []


# ---------------------------------------------------------------------------
# Router failover ladder over stub replicas (engine-free, chaos)
# ---------------------------------------------------------------------------

TOKENS = [11, 22, 33, 44, 55, 66]


async def _stub_replica(resumes, resume_status=200, chunk_gap_s=0.03):
    """A stand-in survivable replica: /v1/completions streams one frame
    per token (with the kgct_token_ids ledger the MIGRATE_URL_HEADER opts
    into), /internal/resume continues after the relayed prefix (or fails
    with ``resume_status``). ``chunk_gap_s`` forces one TCP chunk per
    frame so the router's per-chunk chaos check is deterministic."""
    from aiohttp import web as aioweb

    async def health(request):
        return aioweb.json_response({"status": "ok"})

    async def metrics(request):
        return aioweb.Response(text="", content_type="text/plain")

    def frame(i):
        return (b"data: " + json.dumps(
            {"choices": [{"text": f"t{i} "}],
             "kgct_token_ids": [TOKENS[i]]}).encode() + b"\n\n")

    async def completions(request):
        assert request.headers.get(MIGRATE_URL_HEADER), \
            "router must name the drain-push target on survivable streams"
        resp = aioweb.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        for i in range(len(TOKENS)):
            await resp.write(frame(i))
            await asyncio.sleep(chunk_gap_s)
        await resp.write(b"data: [DONE]\n\n")
        return resp

    async def resume(request):
        envelope = await request.json()
        resumes.append({"url": str(request.url),
                        "rid": request.headers.get(REQUEST_ID_HEADER),
                        "envelope": envelope})
        if resume_status != 200:
            return aioweb.json_response(
                {"error": {"message": "no seat"}}, status=resume_status)
        relayed = envelope["relayed_token_ids"]
        assert envelope["body"]["prompt"] == "survive me"
        resp = aioweb.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            RESUME_MODE_HEADER: "import"})
        await resp.prepare(request)
        for i in range(len(relayed), len(TOKENS)):
            await resp.write(frame(i))
        await resp.write(b"data: [DONE]\n\n")
        return resp

    app = aioweb.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/internal/resume", resume)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"


async def _start_router(router):
    from aiohttp.test_utils import TestClient, TestServer
    client = TestClient(TestServer(router.build_app()))
    await client.start_server()
    return client


def _client_frames(body: bytes):
    """(data payloads, [DONE] seen) of a client-received SSE byte stream."""
    payloads, done = [], False
    for part in body.split(b"\n\n"):
        for line in part.split(b"\n"):
            if not line.startswith(b"data:"):
                continue
            payload = line[5:].strip()
            if payload == b"[DONE]":
                done = True
            elif payload:
                payloads.append(json.loads(payload))
    return payloads, done


@pytest.mark.chaos
class TestRouterMidstreamFailover:
    def test_kill_midstream_splices_one_complete_stream(self, monkeypatch,
                                                        tmp_path):
        """The acceptance pin at the router: replica_kill_midstream severs
        the upstream socket after 2 relayed chunks, and the client still
        sees ONE complete stream — the relayed prefix from the dying
        replica spliced with the successor's /internal/resume continuation
        — with the failover attributed (counter, trace span, flight dump)
        and the token ledger stripped from every client frame."""
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def scenario():
            resumes = []
            r1, u1 = await _stub_replica(resumes)
            r2, u2 = await _stub_replica(resumes)
            router = Router([u1, u2], health_interval_s=9999,
                            fail_threshold=99)
            client = await _start_router(router)
            try:
                configure_faults("replica_kill_midstream:after=2,times=1")
                r = await client.post(
                    "/v1/completions",
                    json={"prompt": "survive me", "max_tokens": 6,
                          "stream": True})
                assert r.status == 200
                body = await r.read()
                payloads, done = _client_frames(body)
                assert done, "client stream must end in [DONE]"
                texts = [p["choices"][0]["text"] for p in payloads]
                assert texts == [f"t{i} " for i in range(6)], texts
                # The replica-embedded ledger never reaches the client.
                assert b"kgct_token_ids" not in body
                # Exactly one resume, on the OTHER replica, carrying the
                # relayed prefix as the replay ledger.
                assert len(resumes) == 1
                assert resumes[0]["envelope"]["relayed_token_ids"] == \
                    TOKENS[:2]
                assert resumes[0]["envelope"]["kind"] == "completion"
                assert router.failovers_total["import"] == 1
                assert router.failovers_total["failed"] == 0
                kinds = [e["kind"] for e in router.flight.export()["events"]]
                assert "failover" in kinds
                dumps = list(tmp_path.glob("flight-midstream_failover-*"))
                assert dumps, "failover must trigger a flight dump"
                # Metrics rows render (pre-seeded outcomes, zeros-safe).
                rm = await client.get("/metrics")
                text = await rm.text()
                assert 'kgct_failovers_total{outcome="import"} 1' in text
                assert 'kgct_failovers_total{outcome="failed"} 0' in text
                assert "kgct_router_failover_seconds" in text
            finally:
                await client.close()
                await r1.cleanup()
                await r2.cleanup()
        asyncio.run(scenario())

    def test_exhausted_ladder_truncates_with_attributed_error(
            self, monkeypatch, tmp_path):
        """Every rung failing (the lone successor 500s its resume) ends the
        stream with a CLEAN error frame carrying the request id, then
        [DONE] — degraded and attributed, never a hang or a silent
        truncation that reads as a finished completion."""
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def scenario():
            resumes = []
            r1, u1 = await _stub_replica(resumes, resume_status=500)
            r2, u2 = await _stub_replica(resumes, resume_status=500)
            router = Router([u1, u2], health_interval_s=9999,
                            fail_threshold=99)
            client = await _start_router(router)
            try:
                configure_faults("replica_kill_midstream:after=2,times=1")
                r = await client.post(
                    "/v1/completions",
                    json={"prompt": "survive me", "stream": True},
                    headers={REQUEST_ID_HEADER: "req-truncated1"})
                body = await r.read()
                payloads, done = _client_frames(body)
                assert done, "even the bottom rung ends in a clean [DONE]"
                errors = [p for p in payloads if "error" in p]
                assert len(errors) == 1
                err = errors[0]["error"]
                assert "truncated" in err["message"]
                assert err["request_id"] == "req-truncated1"
                assert router.failovers_total["failed"] == 1
                assert len(resumes) == 1   # the one successor was tried
                dumps = [json.loads(p.read_text()) for p in
                         tmp_path.glob("flight-midstream_failover-*")]
                assert any(d["reason"] == "midstream_failover"
                           and d["info"].get("outcome") == "failed"
                           for d in dumps)
            finally:
                await client.close()
                await r1.cleanup()
                await r2.cleanup()
        asyncio.run(scenario())

    def test_non_survivable_streams_relay_untouched(self):
        """A single-replica fleet has no failover target: the router must
        not enter parse-mode relay (no MIGRATE_URL_HEADER upstream, bytes
        pass through untouched) — the pre-migration contract holds
        byte-for-byte."""
        from aiohttp import web as aioweb

        from kubernetes_gpu_cluster_tpu.serving.router import Router

        async def scenario():
            seen = {}

            async def completions(request):
                seen["migrate_url"] = request.headers.get(MIGRATE_URL_HEADER)
                resp = aioweb.StreamResponse()
                await resp.prepare(request)
                await resp.write(b"data: {\"kgct_token_ids\": [9]}\n\n")
                await resp.write(b"data: [DONE]\n\n")
                return resp

            async def health(request):
                return aioweb.json_response({"status": "ok"})

            app = aioweb.Application()
            app.router.add_get("/health", health)
            app.router.add_post("/v1/completions", completions)
            runner = aioweb.AppRunner(app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            url = f"http://127.0.0.1:{runner.addresses[0][1]}"
            router = Router([url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                r = await client.post("/v1/completions",
                                      json={"prompt": "x", "stream": True})
                body = await r.read()
                assert seen["migrate_url"] is None
                # No parse-mode: even a stray ledger field passes through.
                assert b"kgct_token_ids" in body
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())


# ---------------------------------------------------------------------------
# Real-engine topology: drain migration + kill-mid-stream (@slow)
# ---------------------------------------------------------------------------

def _serve(runners, servers):
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server

    async def start():
        srv = build_server(_engine_config(), None, "debug-tiny")
        runner = aioweb.AppRunner(srv.build_app())
        await runner.setup()
        site = aioweb.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        runners.append(runner)
        servers.append(srv)
        return srv, f"http://127.0.0.1:{runner.addresses[0][1]}"
    return start()


@pytest.mark.slow
class TestLiveMigrationServing:
    """End-to-end session survivability over real sockets: 2 colocated
    replicas behind the real router; an in-flight stream outlives its
    replica through drain migration (parked-KV import) and through a
    mid-stream kill (token-replay recompute), byte-identical to the
    uninterrupted run in both cases."""

    PROMPT_TEXT_BODY = {"prompt": "the fleet must survive", "max_tokens": 24,
                        "temperature": 0.0}

    async def _topology(self):
        import aiohttp
        from aiohttp import web as aioweb

        from kubernetes_gpu_cluster_tpu.serving.router import Router
        runners, servers = [], []
        await _serve(runners, servers)
        await _serve(runners, servers)
        urls = []
        for runner in runners:
            urls.append(f"http://127.0.0.1:{runner.addresses[0][1]}")
        router = Router(urls, health_interval_s=9999)
        rrunner = aioweb.AppRunner(router.build_app())
        await rrunner.setup()
        rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
        await rsite.start()
        runners.append(rrunner)
        ru = f"http://127.0.0.1:{rrunner.addresses[0][1]}"
        return runners, servers, router, ru, aiohttp.ClientSession()

    @staticmethod
    def _stream_text(body: bytes):
        payloads, done = _client_frames(body)
        assert not any("error" in p for p in payloads), payloads
        return "".join(p["choices"][0]["text"] for p in payloads), done

    def test_drain_migrates_stream_to_peer_import(self, monkeypatch,
                                                  tmp_path):
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def scenario():
            runners, servers, router, ru, sess = await self._topology()
            try:
                async with sess:
                    # Uninterrupted reference (greedy, non-stream).
                    async with sess.post(f"{ru}/v1/completions",
                                         json=self.PROMPT_TEXT_BODY) as r:
                        assert r.status == 200, await r.text()
                        ref = (await r.json())["choices"][0]["text"]
                    body = dict(self.PROMPT_TEXT_BODY, stream=True)
                    async with sess.post(f"{ru}/v1/completions",
                                         json=body) as r:
                        assert r.status == 200
                        it = r.content.__aiter__()
                        first_line = await it.__anext__()   # stream is live
                        src = next(s for s in servers
                                   if s.engine.engine.has_unfinished_requests())
                        dst = next(s for s in servers if s is not src)
                        task = src.begin_drain()
                        assert task is not None
                        chunks = [first_line]
                        async for chunk in r.content:
                            chunks.append(chunk)
                        await asyncio.wait_for(task, timeout=30)
                    text, done = self._stream_text(b"".join(chunks))
                    assert done
                    # One uninterrupted client-visible stream, byte-equal
                    # to the undrained reference run.
                    assert text == ref
                    # Attribution on both sides of the seam + the router.
                    mig_src = src.migration.migrations
                    mig_dst = dst.migration.migrations
                    assert mig_src.get(("push", "ok")) == 1
                    assert mig_dst.get(("recv", "ok")) == 1
                    assert router.failovers_total["import"] == 1
                    src_kinds = [e["kind"] for e in
                                 src.engine.engine.obs.flight.export()
                                 ["events"]]
                    dst_kinds = [e["kind"] for e in
                                 dst.engine.engine.obs.flight.export()
                                 ["events"]]
                    assert "migrate" in src_kinds
                    assert "migrate" in dst_kinds
            finally:
                for runner in reversed(runners):
                    await runner.cleanup()
        asyncio.run(scenario())

    def test_kill_midstream_recomputes_on_successor(self):
        """No drain, no parked KV — the upstream socket is severed by
        chaos and the successor reconstructs the stream by token replay,
        still byte-identical (greedy)."""
        async def scenario():
            runners, servers, router, ru, sess = await self._topology()
            try:
                async with sess:
                    async with sess.post(f"{ru}/v1/completions",
                                         json=self.PROMPT_TEXT_BODY) as r:
                        assert r.status == 200, await r.text()
                        ref = (await r.json())["choices"][0]["text"]
                    configure_faults(
                        "replica_kill_midstream:after=2,times=1")
                    body = dict(self.PROMPT_TEXT_BODY, stream=True)
                    async with sess.post(f"{ru}/v1/completions",
                                         json=body) as r:
                        assert r.status == 200
                        text, done = self._stream_text(await r.read())
                    assert done
                    assert text == ref
                    assert router.failovers_total["recompute"] == 1
                    assert router.failovers_total["failed"] == 0
                    # The dying replica's engine was told to abort its
                    # orphaned sequence eventually (the router closed the
                    # upstream); the resumed side emitted only new tokens.
            finally:
                configure_faults(None)
                for runner in reversed(runners):
                    await runner.cleanup()
        asyncio.run(scenario())


@pytest.mark.slow
def test_bench_drain_phase_structure():
    """The KGCT_BENCH_DRAIN A/B end-to-end: both arms deliver EVERY client
    stream (survivability is not the variable — drain time is), the
    migrate arm actually migrated, the wait arm actually fell back, and
    the headline ratio is present. On one CPU core the separation is
    structural (transfer-bound vs decode-bound), so only a loose bound
    guards against the migration path itself slowing the drain down."""
    import bench

    out = bench._measure_drain()
    for arm in ("wait", "migrate"):
        assert out[arm]["complete_streams"] == out[arm]["sessions"], arm
        assert out[arm]["drain_seconds"] > 0
    assert out["migrate"]["migrations_push_ok"] > 0
    assert out["wait"]["migrations_push_fallback"] > 0
    assert out["wait"]["migrations_push_ok"] == 0
    resumed = out["migrate"]["failovers"]
    assert resumed["import"] + resumed["recompute"] > 0
    assert resumed["failed"] == 0
    assert out["drain_migrate_over_wait_seconds"] is not None
    # Loose regression bound, not a perf pin (the bench's job to measure).
    assert out["drain_migrate_over_wait_seconds"] < 1.5
