"""Multi-tenant QoS: weighted fair scheduling, priority preemption,
per-tier admission budgets, and the QoS-off byte-identity contract.

Scheduler-level tests construct Scheduler directly (no jit, ~ms each);
engine-level pins share ONE module-scoped debug-tiny engine pair for the
tier-1 budget. The tenant_flood chaos test drives the admission ledger
directly (engine-free of device work).
"""

import dataclasses

import pytest

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               QoSTier, SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.config.qos import (parse_qos_tiers,
                                                   resolve_tier_name,
                                                   tiers_to_json)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.scheduler import Scheduler
from kubernetes_gpu_cluster_tpu.engine.sequence import Sequence

TIERS = (QoSTier("interactive", weight=4.0, priority=10),
         QoSTier("batch", weight=1.0, priority=0))


def _cfg(num_pages=64, page_size=4, max_num_seqs=4, decode_window=1,
         max_prefill_tokens=64, qos=True, mixed=False, swap_gb=0.0):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages,
                          swap_space_gb=swap_gb),
        scheduler=SchedulerConfig(
            max_num_seqs=max_num_seqs,
            max_prefill_tokens=max_prefill_tokens,
            decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(16, 32, 64),
            decode_window=decode_window,
            mixed_batch_enabled=mixed,
            qos_tiers=TIERS if qos else ()))


def _seq(rid, n_prompt, tier=None, max_tokens=64):
    return Sequence(rid, list(range(1, n_prompt + 1)),
                    SamplingParams(max_tokens=max_tokens, qos_tier=tier))


class TestTierParsing:
    def test_default_literal_and_round_trip(self):
        tiers = parse_qos_tiers("default")
        assert [t.name for t in tiers] == ["interactive", "batch"]
        assert tiers[0].weight == 4.0 and tiers[0].priority == 10
        # tiers_to_json -> parse_qos_tiers round-trips (the renderer path)
        assert parse_qos_tiers(tiers_to_json(tiers)) == tiers

    def test_empty_disables(self):
        assert parse_qos_tiers(None) == ()
        assert parse_qos_tiers("") == ()

    def test_validation_failures(self):
        with pytest.raises(ValueError, match="label"):
            parse_qos_tiers('{"bad name!": {}}')
        with pytest.raises(ValueError, match="unknown key"):
            parse_qos_tiers('{"a": {"wieght": 2}}')
        with pytest.raises(ValueError, match="weight"):
            parse_qos_tiers('{"a": {"weight": 0}}')
        with pytest.raises(ValueError, match="max_concurrent"):
            parse_qos_tiers('{"a": {"max_concurrent": 0}}')
        with pytest.raises(ValueError, match="pinned to both"):
            parse_qos_tiers('{"a": {"users": ["u"]}, '
                            '"b": {"users": ["u"]}}')
        with pytest.raises(ValueError, match="JSON"):
            parse_qos_tiers("{nope")

    def test_resolution_order(self):
        tiers = parse_qos_tiers(
            '{"vip": {"priority": 5, "users": ["alice"]}, "std": {}}')
        # header beats user pin beats default (= first tier)
        assert resolve_tier_name(tiers, None, header="std",
                                 tenant_key="alice") == ("std", None)
        assert resolve_tier_name(tiers, None,
                                 tenant_key="alice") == ("vip", None)
        assert resolve_tier_name(tiers, None,
                                 tenant_key="bob") == ("vip", None)
        assert resolve_tier_name(tiers, "std",
                                 tenant_key="bob") == ("std", None)
        name, err = resolve_tier_name(tiers, None, header="nope")
        assert name is None and "unknown qos tier" in err
        # QoS off: nothing resolves, header ignored
        assert resolve_tier_name((), None, header="x") == (None, None)

    def test_sampling_params_state_round_trip(self):
        p = SamplingParams(max_tokens=4, qos_tier="batch")
        assert SamplingParams.from_state(p.to_state()).qos_tier == "batch"
        with pytest.raises(ValueError, match="qos_tier"):
            SamplingParams(qos_tier=7)


class TestFairShareScheduling:
    def test_promotion_ahead_of_queued_batch(self):
        """An interactive request queued behind batch prompts is promoted
        to the head (weighted fair admission), FCFS within each tier."""
        s = Scheduler(_cfg(max_num_seqs=8), 64)
        for i in range(3):
            s.add(_seq(f"b{i}", 8, "batch"))
        s.add(_seq("chat0", 8, "interactive"))
        s.add(_seq("chat1", 8, "interactive"))
        batch = s.schedule()
        ids = [x.request_id for x in batch.seqs]
        assert ids[0] == "chat0"                 # promoted, FCFS in-tier
        assert ids.index("chat0") < ids.index("chat1")

    def test_weighted_interleaving_no_starvation(self):
        """With both tiers continuously backlogged and one admission slot
        per round, service follows the 4:1 weights — and batch is never
        starved (its clock falls behind and wins the comparison)."""
        s = Scheduler(_cfg(max_num_seqs=1, num_pages=256), 256)
        order = []
        backlog = {"interactive": 0, "batch": 0}

        def refill():
            for tier in ("interactive", "batch"):
                while backlog[tier] < 2:
                    rid = f"{tier[0]}{len(order) + backlog[tier]}-{tier}"
                    s.add(_seq(rid, 8, tier, max_tokens=1))
                    backlog[tier] += 1

        for _ in range(30):
            refill()
            batch = s.schedule()
            assert batch is not None and batch.kind == "prefill"
            seq = batch.seqs[0]
            tier = seq.params.qos_tier
            order.append(tier)
            backlog[tier] -= 1
            # retire immediately: frees the single seat for the next round
            s.finish(seq, __import__(
                "kubernetes_gpu_cluster_tpu.engine.sequence",
                fromlist=["FinishReason"]).FinishReason.LENGTH)
        n_int = order.count("interactive")
        n_bat = order.count("batch")
        assert n_bat >= 3, f"batch starved: {order}"
        # 4:1 weights with equal-size requests -> ~4:1 service split
        assert 2.0 <= n_int / n_bat <= 8.0, order

    def test_chunk_defer_bounds_interactive_wait(self):
        """Deficit bound: a batch-tier long prompt mid-chunk yields the
        prefill budget to a newly arrived interactive request — the
        interactive prefill schedules next, not after every remaining
        chunk."""
        s = Scheduler(_cfg(max_prefill_tokens=16, num_pages=64), 64)
        s.add(_seq("long-batch", 48, "batch"))        # 3 chunks of 16
        b1 = s.schedule()
        assert b1.kind == "prefill" and b1.partial    # chunk 1 of 3
        s.add(_seq("chat", 8, "interactive"))
        b2 = s.schedule()
        assert [x.request_id for x in b2.seqs] == ["chat"]
        # the batch head kept its pages and resumes chunking afterwards
        assert s.waiting[0].request_id == "long-batch"
        assert s.waiting[0].num_prefilled == 16
        b3 = s.schedule()
        assert b3.seqs[0].request_id == "long-batch"

    def test_deferred_prefix_held_head_keeps_its_pages(self):
        """Review regression: a prefix-cache-hit head (num_prefilled > 0,
        holding refcounted cache pages, prompt small enough to pack) whose
        chunk is QoS-deferred must NOT be admitted by the lookahead loop
        as a full prefill — that would overwrite seq.pages and leak the
        cached pages. It advances only through the chunk path once the
        defer gate releases."""
        cfg = EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=4, num_pages=64),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_prefill_tokens=64,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=(16, 32, 64),
                decode_window=1, mixed_batch_enabled=False,
                enable_prefix_caching=True, qos_tiers=TIERS))
        cfg = dataclasses.replace(
            cfg, scheduler=dataclasses.replace(cfg.scheduler,
                                               max_num_seqs=1))
        s = Scheduler(cfg, 64)
        from kubernetes_gpu_cluster_tpu.engine.sequence import FinishReason

        def seq_with(rid, toks, tier):
            return Sequence(rid, toks,
                            SamplingParams(max_tokens=64, qos_tier=tier))

        # Warm the prefix cache with a batch prompt, drain it fully.
        warm = seq_with("warm", list(range(1, 13)), "batch")
        s.add(warm)
        assert s.schedule().kind == "prefill"
        s.finish(warm, FinishReason.LENGTH)
        # A batch occupier holds the ONLY seat, so the same-prefix batch
        # head's cache hit (pages + num_prefilled>0) happens while its
        # final chunk is seat-blocked — the prefix-held-head-at-waiting[0]
        # state the defer gate then sees.
        occ = seq_with("occ", list(range(50, 58)), "batch")
        s.add(occ)
        assert s.schedule().kind == "prefill"
        bat = seq_with("bat", list(range(1, 13)), "batch")
        s.add(bat)
        assert s.schedule().kind == "decode"        # occ decodes; bat blocked
        assert bat.num_prefilled > 0 and bat.pages  # cache hit held
        held = list(bat.pages)
        s.finish(occ, FinishReason.LENGTH)          # seat frees
        chat = seq_with("chat", list(range(100, 108)), "interactive")
        s.add(chat)
        batch = s.schedule()
        ids = [x.request_id for x in batch.seqs]
        assert ids == ["chat"]                      # defer fired, chat first
        assert bat.pages == held                    # held pages untouched
        s.finish(chat, FinishReason.LENGTH)
        # gate releases: the batch head finishes through the chunk path
        nxt = s.schedule()
        assert [x.request_id for x in nxt.seqs] == ["bat"]
        assert bat.pages[:len(held)] == held

    def test_chunkable_waiter_never_deadlocks_the_chunk_gate(self):
        """Review regression: when the owed higher-priority waiter is
        ITSELF chunkable (prompt > max_prefill_tokens), deferring the
        mid-chunk head would schedule neither sequence and freeze both
        clocks — a permanent stall. The gate must not fire: the head
        keeps chunking, then the waiter runs, and both finish."""
        s = Scheduler(_cfg(max_prefill_tokens=16, num_pages=64), 64)
        s.add(_seq("long-batch", 48, "batch", max_tokens=1))
        assert s.schedule().partial          # chunk 1 of 3, mid-chunk head
        s.add(_seq("long-chat", 40, "interactive", max_tokens=1))
        scheduled = []
        for _ in range(12):
            batch = s.schedule()
            if batch is None:
                break
            scheduled.append(batch.seqs[0].request_id)
            for seq in batch.seqs:
                if (seq in s.running
                        and seq.num_prefilled >= seq.num_tokens):
                    seq.append_token(1)      # simulate its one token
                    from kubernetes_gpu_cluster_tpu.engine.sequence import (
                        FinishReason)
                    s.finish(seq, FinishReason.LENGTH)
        assert not s.has_work(), f"stalled with work queued: {scheduled}"
        assert {"long-batch", "long-chat"} <= set(scheduled)

    def test_idle_tier_banks_no_credit_even_reactivating_alone(self):
        """Review regression: a tier re-activating while NO settled tier
        remains active must still floor to the monotone system virtual
        time, not keep the stale low clock it banked while idle."""
        from kubernetes_gpu_cluster_tpu.engine.qos import QoSAccounting
        q = QoSAccounting(TIERS)
        q.sync_active(["interactive", "batch"])
        q.charge("interactive", 4000)        # w=4 -> clock 1000
        q.charge("batch", 100)               # w=1 -> clock 100
        q.sync_active(["interactive"])       # batch goes idle; vtime=100
        q.charge("interactive", 16000)       # clock 5000; batch idle
        q.sync_active(["interactive"])       # vtime high-waters to 5000
        q.sync_active([])                    # everyone idle
        q.sync_active(["batch"])             # batch re-enters ALONE
        assert q.virtual_tokens["batch"] >= 5000.0
        # interactive returning is never punished below its own clock
        q.sync_active(["interactive", "batch"])
        assert q.virtual_tokens["interactive"] == 5000.0

    def test_idle_departure_observed_during_waiting_empty_stretch(self):
        """Review regression: sync_active must run on EVERY schedule()
        call (waiting-empty decode stretches included) — otherwise a
        tier's departure is never observed, and its later return skips
        the idle catch-up and spends arbitrarily large banked credit."""
        s = Scheduler(_cfg(num_pages=256, decode_window=4), 256)
        bat = _seq("bat", 8, "batch", max_tokens=64)
        s.add(bat)
        assert s.schedule().kind == "prefill"
        # Pure-decode stretch with waiting EMPTY: batch's clock charges
        # far ahead while no other tier has work.
        for _ in range(10):
            bat.append_token(3)
            assert s.schedule().kind == "decode"
        vt_batch = s.qos.virtual_tokens["batch"]
        assert vt_batch > 8
        # Interactive re-enters AFTER the stretch: it must floor to the
        # system virtual time (~batch's clock), not its stale 0.
        s.add(_seq("chat", 8, "interactive"))
        s.schedule()
        assert s.qos.virtual_tokens["interactive"] >= vt_batch - 4 - 1

    def test_make_room_preempts_batch_for_interactive(self):
        """Seats full of batch-tier decodes: an interactive arrival evicts
        the youngest batch sequence (recompute here; swap when the host
        tier is on) and the victim requeues BEHIND its beneficiary."""
        s = Scheduler(_cfg(max_num_seqs=2), 64)
        s.add(_seq("b0", 8, "batch"))
        s.add(_seq("b1", 8, "batch"))
        assert s.schedule().kind == "prefill"
        s.add(_seq("chat", 8, "interactive"))
        batch = s.schedule()
        assert any(x.request_id == "chat" for x in batch.seqs)
        assert s.num_preemptions_by_kind["recompute"] == 1
        # victim (youngest batch) sits behind the interactive beneficiary
        assert [q.request_id for q in s.waiting] == ["b1"]

    def test_same_tier_never_preempts_for_admission(self):
        """Within one tier the no-preempt-for-admission invariant holds:
        a batch arrival never evicts running batch work."""
        s = Scheduler(_cfg(max_num_seqs=2), 64)
        s.add(_seq("b0", 8, "batch"))
        s.add(_seq("b1", 8, "batch"))
        s.schedule()
        s.add(_seq("b2", 8, "batch"))
        batch = s.schedule()
        assert batch.kind == "decode"
        assert s.num_preemptions == 0

    def test_decode_growth_victim_is_batch_not_interactive(self):
        """Page-pressure preemption picks the batch-tier victim even when
        an interactive sequence is the youngest admission."""
        cfg = _cfg(num_pages=5, page_size=2, max_num_seqs=4)  # 4 usable
        s = Scheduler(cfg, 5)
        b, a = _seq("bat", 2, "batch"), _seq("int", 2, "interactive")
        s.add(b)
        s.add(a)        # interactive admitted LAST (= legacy victim)
        assert s.schedule().kind == "prefill"     # 1 page each, 2 free
        b.append_token(5)
        a.append_token(6)
        b.append_token(5)
        a.append_token(6)
        b.append_token(5)
        a.append_token(6)
        # both need a 2nd and 3rd page; pool can't fit both -> preempt
        batch = s.schedule()
        assert batch is not None
        assert b.request_id not in [x.request_id for x in batch.seqs]
        assert s.num_preemptions == 1
        assert s.waiting and s.waiting[0].request_id == "bat"

    def test_batch_requester_never_evicts_interactive(self):
        """A lower-priority sequence must stop growing rather than evict a
        higher-priority one (interactive only preempted by its own
        tier)."""
        cfg = _cfg(num_pages=5, page_size=2, max_num_seqs=4)
        s = Scheduler(cfg, 5)
        a, b = _seq("int", 2, "interactive"), _seq("bat", 2, "batch")
        s.add(a)
        s.add(b)        # batch youngest -> it is the only eligible victim
        s.schedule()
        for _ in range(3):
            a.append_token(6)
            b.append_token(5)
        batch = s.schedule()
        # under pressure the batch seq self-evicts (its own tier), never
        # the interactive one
        assert batch is not None
        assert a.request_id in [x.request_id for x in batch.seqs]
        assert s.num_preemptions == 1
        assert s.waiting[0].request_id == "bat"

    def test_qos_off_has_no_accounting(self):
        """No tiers configured -> scheduler.qos is None and params carrying
        a qos_tier are inert (the byte-identity contract's structural
        half)."""
        s = Scheduler(_cfg(qos=False), 64)
        assert s.qos is None
        s.add(_seq("x", 8, "interactive"))
        assert s.schedule() is not None


# -- engine-level pins (shared module engines, tier-1 budget) ---------------

@pytest.fixture(scope="module")
def qos_engine():
    return LLMEngine(_cfg(num_pages=128, max_num_seqs=4, decode_window=2,
                          max_prefill_tokens=16, qos=True),
                     eos_token_id=None)


def _drain(engine):
    outs = {}
    order = []
    while engine.has_unfinished_requests():
        for o in engine.step():
            if o.new_token_ids and o.request_id not in order:
                order.append(o.request_id)
            outs[o.request_id] = o       # keep the LAST (finished) output
    return outs, order


class TestEngineFairness:
    def test_interactive_first_token_beats_mid_chunk_batch(self, qos_engine):
        """Engine-level deficit-bound pin: a batch-tier long prompt
        (chunked across 3 prefill steps) cannot push an interactive
        arrival's first schedule past its deficit bound — the interactive
        request's FIRST token lands before the batch request's."""
        eng = qos_engine
        eng.add_request("long-batch", list(range(1, 49)),
                        SamplingParams(max_tokens=4, temperature=0.0,
                                       qos_tier="batch"))
        eng.step()                     # chunk 1 of [0:16) committed
        eng.add_request("chat", [7, 8, 9],
                        SamplingParams(max_tokens=4, temperature=0.0,
                                       qos_tier="interactive"))
        outs, first_token_order = _drain(eng)
        assert set(outs) == {"long-batch", "chat"}
        assert all(o.finished for o in outs.values())
        assert first_token_order[0] == "chat"
        # the deferred batch chunk resumed and completed unharmed
        assert len(outs["long-batch"].output_token_ids) == 4

    def test_batch_victim_selected_before_interactive(self, qos_engine):
        """Engine-level preemption-order pin: under page pressure the
        batch-tier sequence is the victim, never the younger interactive
        one — and everyone still finishes (reset-then-converge)."""
        eng = LLMEngine(_cfg(num_pages=7, page_size=4, max_num_seqs=4,
                             decode_window=2, qos=True), eos_token_id=None)
        eng.add_request("bat", [1, 2, 3, 4],
                        SamplingParams(max_tokens=20, temperature=0.0,
                                       qos_tier="batch"))
        eng.add_request("int", [5, 6, 7, 8],
                        SamplingParams(max_tokens=20, temperature=0.0,
                                       qos_tier="interactive"))
        outs, _ = _drain(eng)
        assert all(len(o.output_token_ids) == 20 for o in outs.values())
        kinds = [(e.request_id, e.kind)
                 for e in eng.obs.tracer.events() if e.kind == "preempt"]
        assert kinds, "expected page-pressure preemptions"
        assert all(rid == "bat" for rid, _ in kinds)

    def test_per_tier_slo_and_metrics_zero_safe(self, qos_engine):
        """A QoS engine renders the tier-labeled series (bounded to the
        configured names) and they are zeros/1.0-safe whatever has run."""
        from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics
        text = Metrics(qos_engine).render()
        assert 'kgct_slo_ttft_attainment_ratio{tier="interactive"}' in text
        assert 'kgct_slo_ttft_attainment_ratio{tier="batch"}' in text
        assert 'kgct_qos_requests_finished_total{tier="batch"}' in text
        assert "nan" not in text
        # bounded cardinality: only configured names appear as tier labels
        import re
        labels = set(re.findall(r'tier="([^"]+)"', text))
        assert labels == {"interactive", "batch"}

    def test_tierless_engine_renders_no_tier_labels(self):
        from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics
        eng = LLMEngine(_cfg(qos=False), eos_token_id=None)
        assert 'tier="' not in Metrics(eng).render()

    def test_tier_slo_falls_back_to_operator_admission_bar(self):
        """Review regression: a tier without its own ttft_budget_ms must
        grade against the OPERATOR's admission default (the bar the
        global tracker and per-tier admission use), not the hardcoded
        north-star default."""
        from kubernetes_gpu_cluster_tpu.observability import Observability
        obs = Observability()
        obs.configure_qos_tiers(
            (QoSTier("strict", ttft_budget_ms=100.0), QoSTier("lax")),
            "strict", fallback_budget_ms=5000.0)
        assert obs.slo_by_tier["strict"].budget_ms == 100.0
        assert obs.slo_by_tier["lax"].budget_ms == 5000.0
        # no operator default -> the north-star default, same as global
        obs.configure_qos_tiers((QoSTier("lax"),), "lax")
        assert obs.slo_by_tier["lax"].budget_ms == obs.slo.budget_ms


class TestByteIdentity:
    def test_uniform_tier_qos_matches_qos_off(self):
        """Byte-identity pin: with every request in ONE uniform tier the
        QoS machinery must be a no-op — greedy AND seeded-sampled outputs
        (penalties included), preemption counts, and step-kind totals all
        equal the tier-less engine's on an identical page-pressured
        workload. Together with the structural pin (no tiers -> qos is
        None -> no QoS branch runs) this pins QoS-off behavior to the
        pre-QoS scheduler."""
        one_tier = (QoSTier("only", weight=1.0, priority=0),)
        outs = {}
        kinds = {}
        for label, tiers in (("off", ()), ("on", one_tier)):
            cfg = EngineConfig(
                model=get_model_config("debug-tiny"),
                cache=CacheConfig(page_size=8, num_pages=8),
                scheduler=SchedulerConfig(
                    max_num_seqs=4, max_prefill_tokens=256,
                    decode_buckets=(1, 2, 4, 8),
                    prefill_buckets=(32, 64, 128, 256),
                    qos_tiers=tiers))
            eng = LLMEngine(cfg, eos_token_id=None)
            assert (eng.scheduler.qos is None) == (label == "off")
            prompts = [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]]
            params = [
                SamplingParams(max_tokens=16, temperature=0.8, seed=11,
                               frequency_penalty=1.5,
                               presence_penalty=0.5,
                               qos_tier="only" if tiers else None),
                SamplingParams(max_tokens=16, temperature=0.8, seed=22,
                               qos_tier="only" if tiers else None),
                SamplingParams(max_tokens=16, temperature=0.0,
                               qos_tier="only" if tiers else None),
            ]
            outs[label] = [o.output_token_ids
                           for o in eng.generate(prompts, params)]
            kinds[label] = (dict(eng.obs.step_kind_counts),
                            eng.scheduler.num_preemptions)
            assert eng.scheduler.num_preemptions > 0  # pressured workload
        assert outs["on"] == outs["off"]
        assert kinds["on"] == kinds["off"]


# -- admission budgets + tenant_flood chaos ---------------------------------

class TestTierAdmission:
    def _admission(self, engine):
        from kubernetes_gpu_cluster_tpu.resilience.deadline import (
            AdmissionController)
        adm = AdmissionController(engine)
        adm.configure_tiers(
            (QoSTier("interactive", weight=4, priority=10,
                     max_concurrent=8),
             QoSTier("batch", weight=1, priority=0, max_concurrent=2)),
            "interactive")
        return adm

    def test_max_concurrent_sheds_only_its_tier(self, qos_engine):
        adm = self._admission(qos_engine)
        adm.on_admit("batch")
        adm.on_admit("batch")
        assert adm.check(None, tier="batch") is not None    # at budget
        assert adm.check(None, tier="interactive") is None  # untouched
        assert adm.shed_by_tier == {"interactive": 0, "batch": 1}
        adm.on_release("batch")
        assert adm.check(None, tier="batch") is None        # budget freed

    def test_tier_ttft_budget_applies_without_header(self, qos_engine):
        from kubernetes_gpu_cluster_tpu.resilience.deadline import (
            AdmissionController)
        from kubernetes_gpu_cluster_tpu.resilience.faults import (
            configure_faults)
        adm = AdmissionController(qos_engine)
        adm.configure_tiers(
            (QoSTier("strict", ttft_budget_ms=100.0),), "strict")
        configure_faults("queue_wait_est:value=30")
        try:
            # tier budget (100 ms) < forced 30 s estimate -> shed, and the
            # shed is attributed to the tier
            ra = adm.check(None, tier="strict")
            assert ra is not None and ra >= 1
            assert adm.shed_by_tier["strict"] == 1
            # an explicit per-request budget still wins over the tier's
            assert adm.check(120000.0, tier="strict") is None
        finally:
            configure_faults(None)

    @pytest.mark.chaos
    def test_tenant_flood_isolated_to_batch_tier(self, qos_engine):
        """The tenant_flood chaos site inflates the LOWEST-priority tier's
        offered load past its budget: every batch check sheds, the
        interactive tier's shed count stays 0, and the hub's per-tier
        series carries the attribution."""
        from kubernetes_gpu_cluster_tpu.resilience import ResilienceHub
        from kubernetes_gpu_cluster_tpu.resilience.drain import DrainState
        from kubernetes_gpu_cluster_tpu.resilience.faults import (
            configure_faults)
        from kubernetes_gpu_cluster_tpu.resilience.watchdog import (
            StepWatchdog)
        adm = self._admission(qos_engine)
        configure_faults("tenant_flood:value=8")
        try:
            for _ in range(5):
                assert adm.check(None, tier="batch") is not None
                assert adm.check(None, tier="interactive") is None
        finally:
            configure_faults(None)
        assert adm.shed_by_tier == {"interactive": 0, "batch": 5}
        wd = StepWatchdog(timeout_s=1000)
        lines = ResilienceHub(adm, wd, DrainState()).render_prometheus()
        text = "\n".join(lines)
        assert 'kgct_requests_shed_total{tier="batch"} 5' in text
        assert 'kgct_requests_shed_total{tier="interactive"} 0' in text
        assert "kgct_requests_shed_total 5" in text


class TestKVHandoffTierGate:
    def test_handoff_gate_attributes_to_forwarded_tier(self):
        """Review regression: the /internal/kv_handoff admission gate must
        run against the tier the decode replica forwarded (header >
        tenant key > default), never the default tier — a batch-classed
        pull's shed lands on the batch ledger."""
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kubernetes_gpu_cluster_tpu.resilience.faults import (
            configure_faults)
        from kubernetes_gpu_cluster_tpu.serving.api_server import (
            build_server)
        tiers = (QoSTier("interactive", weight=4, priority=10),
                 QoSTier("batch", weight=1, priority=0, max_concurrent=2))
        cfg = dataclasses.replace(
            _cfg(qos=False),
            scheduler=dataclasses.replace(_cfg().scheduler,
                                          qos_tiers=tiers))
        server = build_server(cfg)

        async def scenario():
            client = TestClient(TestServer(server.build_app()))
            await client.start_server()
            try:
                configure_faults("tenant_flood:value=8")
                r = await client.post(
                    "/internal/kv_handoff",
                    json={"prompt_token_ids": [1, 2, 3]},
                    headers={"x-kgct-qos-tier": "batch"})
                assert r.status == 429
                # the shed is the BATCH tier's, not the default's
                assert server.admission.shed_by_tier == {
                    "interactive": 0, "batch": 1}
                # interactive-classed pulls stay admitted under the flood
                r2 = await client.post(
                    "/internal/kv_handoff",
                    json={"prompt_token_ids": [1, 2, 3]},
                    headers={"x-kgct-qos-tier": "interactive"})
                assert r2.status == 200
            finally:
                configure_faults(None)
                await client.close()

        asyncio.run(scenario())


# -- router tier resolution + ledger (engine-free) --------------------------

class TestRouterQoS:
    def _router(self):
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        return Router(["http://a", "http://b"],
                      qos_tiers=parse_qos_tiers(
                          '{"vip": {"priority": 5, "users": ["alice"]}, '
                          '"std": {}}'))

    def test_resolution_and_propagation(self):
        class Req:
            def __init__(self, headers):
                self.headers = headers
        r = self._router()
        # valid header wins and is propagated as-is
        assert r._qos_resolve(Req({"x-kgct-qos-tier": "std"}),
                              {"user": "alice"}) == ("std", "std")
        # user pin resolves when no header
        assert r._qos_resolve(Req({}), {"user": "alice"}) == ("vip", "vip")
        # default tier (first configured) otherwise
        assert r._qos_resolve(Req({}), {"user": "bob"}) == ("vip", "vip")
        # invalid header: nothing resolved, nothing propagated (the
        # replica's loud 400 to give)
        assert r._qos_resolve(Req({"x-kgct-qos-tier": "nope"}),
                              None) == (None, None)

    def test_tier_inflight_metrics_zero_safe(self):
        r = self._router()
        assert r.tier_inflight == {"vip": 0, "std": 0}
        # a tier-less router carries no ledger and renders no tier series
        from kubernetes_gpu_cluster_tpu.serving.router import Router
        assert Router(["http://a"]).tier_inflight == {}
