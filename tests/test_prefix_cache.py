"""Automatic prefix caching: correctness, reuse accounting, eviction.

The bar: with caching ON, outputs are IDENTICAL to caching OFF (reused pages
hold exactly the KV the prefill would have recomputed), repeated prompts skip
page-aligned prefix compute, and cache entries evict cleanly under pool
pressure without touching pages live sequences still share.
"""

import numpy as np

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.kv_cache import (CachingPageAllocator,
                                                        PrefixCache)


def _engine(prefix_caching=True, num_pages=129, max_prefill_tokens=256):
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=num_pages),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=max_prefill_tokens,
            decode_buckets=(1, 2, 4), prefill_buckets=(32, 64, 128, 256),
            enable_prefix_caching=prefix_caching))
    return LLMEngine(cfg)


def test_repeated_prompt_hits_cache_and_matches():
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 500, 50).tolist()
    params = SamplingParams(max_tokens=6, temperature=0.0)

    ref = _engine(prefix_caching=False).generate([prompt], params)[0]

    eng = _engine(prefix_caching=True)
    first = eng.generate([prompt], params)[0]
    assert first.output_token_ids == ref.output_token_ids
    assert eng.scheduler.prefix_cache.hits == 0
    # 50 tokens / page 8 => 6 full pages cached
    assert len(eng.scheduler.prefix_cache) == 6

    second = eng.generate([prompt], params)[0]
    assert second.output_token_ids == ref.output_token_ids
    assert eng.scheduler.prefix_cache.hits == 1

    # The /metrics surface sees the same counts: hit ratio nonzero once a
    # hit happened (fresh-scrape zero-state is pinned in test_serving.py).
    from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics
    lines = Metrics(eng).render().splitlines()
    [ratio] = [l for l in lines
               if l.startswith("kgct_prefix_cache_hit_ratio ")]
    assert float(ratio.split()[-1]) == 0.5          # 1 hit / 2 lookups
    [hits] = [l for l in lines
              if l.startswith("kgct_prefix_cache_hits_total ")]
    assert int(hits.split()[-1]) == 1


def test_shared_prefix_diverging_tail():
    rng = np.random.default_rng(1)
    shared = rng.integers(1, 500, 24).tolist()       # 3 full pages
    a = shared + rng.integers(1, 500, 10).tolist()
    b = shared + rng.integers(1, 500, 13).tolist()
    params = SamplingParams(max_tokens=5, temperature=0.0)

    ref_eng = _engine(prefix_caching=False)
    ref = [o.output_token_ids for o in ref_eng.generate([a, b], params)]

    eng = _engine(prefix_caching=True)
    out_a = eng.generate([a], params)[0].output_token_ids
    out_b = eng.generate([b], params)[0].output_token_ids
    assert [out_a, out_b] == ref
    assert eng.scheduler.prefix_cache.hits == 1      # b reused a's prefix


def test_fully_cached_prompt_leaves_last_token():
    """A prompt whose every page is cached must still prefill >=1 token (the
    sampler reads the last prompt token's hidden state)."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(1, 500, 32).tolist()       # exactly 4 pages
    params = SamplingParams(max_tokens=4, temperature=0.0)
    ref = _engine(prefix_caching=False).generate([prompt], params)[0]
    eng = _engine(prefix_caching=True)
    first = eng.generate([prompt], params)[0]
    second = eng.generate([prompt], params)[0]
    assert first.output_token_ids == ref.output_token_ids
    assert second.output_token_ids == ref.output_token_ids


def test_eviction_under_pressure_and_shared_page_safety():
    alloc = CachingPageAllocator(num_pages=9, page_size=8)   # 8 usable
    cache = alloc.prefix_cache
    toks = list(range(16))                                   # 2 pages
    pages = alloc.allocate(2)
    cache.register(toks, pages)                              # cache refs +1
    # a live sequence shares the first page
    reused, matched = cache.lookup(toks)
    assert matched == 16 and reused == pages
    alloc.free(pages)                                        # original owner gone
    assert alloc.num_free == 6
    # pool pressure: need 7 pages -> evicts both entries; the shared pages
    # survive for the live sequence (refcount), so only 0 extra freed beyond
    # nothing... the two cached pages are still referenced by `reused`.
    assert not alloc.can_allocate(7)
    assert len(cache) == 0                                   # entries dropped
    assert alloc.num_free == 6                               # pages still live
    alloc.free(reused)                                       # last refs drop
    assert alloc.num_free == 8
    assert alloc.can_allocate(7)


def test_cache_off_by_default():
    eng = _engine(prefix_caching=False)
    assert eng.scheduler.prefix_cache is None


def test_evicting_parent_drops_unreachable_children():
    """Chained entries: evicting page i's entry must take page i+1's entry
    with it — a child without its parent is unreachable by lookup and would
    pin its page forever."""
    alloc = CachingPageAllocator(num_pages=9, page_size=8)
    cache = alloc.prefix_cache
    toks = list(range(24))                           # 3 chained pages
    pages = alloc.allocate(3)
    cache.register(toks, pages)
    alloc.free(pages)                                # only cache refs remain
    assert len(cache) == 3 and alloc.num_free == 5
    dropped = cache.evict(1)                         # LRU head = page 0
    assert dropped == 3, "descendants must go with the parent"
    assert len(cache) == 0
    assert alloc.num_free == 8
