"""Shard-aware streaming weight load (engine/weights._load_streamed).

VERDICT r4 weak #4: the full-stack loader materialized the whole model in
host RAM before sharded placement, putting llama-3-70b (BASELINE config 5)
physically out of reach. The streamed path reads only each host's shard
byte ranges from the safetensors (ranged reads), so per-host RSS is
~model/world. These tests prove:

1. full-vs-streamed PARITY (bf16/f32 and int8) on every param, on tp and pp
   meshes — including the row-sharded quantization scales that must match
   the global per-output-channel amax bit-for-bit;
2. on a 2-PROCESS mesh over a multi-file checkpoint, each process's python
   (numpy) peak stays far below the full model bytes while the loaded
   shards are exactly the process's half;
3. the 70B load PLAN: modeled per-host bytes on the BASELINE config 5 mesh
   stay under 40 GB.
"""

from __future__ import annotations

import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")

import jax

from kubernetes_gpu_cluster_tpu.config import get_model_config
from kubernetes_gpu_cluster_tpu.engine.engine import resolve_shardings
from kubernetes_gpu_cluster_tpu.engine.weights import (
    config_from_hf, load_weights)
from kubernetes_gpu_cluster_tpu.parallel import make_mesh


def _ckpt_dir(tmp_path, moe=False, shards=None):
    if moe:
        from transformers import MixtralConfig, MixtralForCausalLM
        cfg = MixtralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            max_position_embeddings=256)
        torch.manual_seed(1)
        model = MixtralForCausalLM(cfg)
    else:
        from transformers import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256)
        torch.manual_seed(0)
        model = LlamaForCausalLM(cfg)
    model.eval()
    d = tmp_path / ("moe" if moe else "dense")
    kw = {"max_shard_size": shards} if shards else {}
    model.save_pretrained(d, safe_serialization=True, **kw)
    return str(d)


def _trees_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for xa, xb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.parametrize("mesh_kw,quant", [
    ({"tp": 2}, None), ({"pp": 2}, None), ({"pp": 2, "tp": 2}, None),
    ({"tp": 2}, "int8"), ({"pp": 2}, "int8"), ({"pp": 2, "tp": 2}, "int8"),
    # int4 runs the most complete mesh only (tier-1 budget): pp x tp covers
    # the layer split, column-sharded scales AND the group-aligned
    # row-shard quantize in one load.
    ({"pp": 2, "tp": 2}, "int4"),
])
def test_streamed_matches_full(tmp_path, mesh_kw, quant):
    path = _ckpt_dir(tmp_path)
    # int4 group size 32: divides the tiny model's matmul input dims (64 /
    # 128) AND the tp=2 row-shard boundaries, exercising the group-aligned
    # shard-quantize == global-quantize contract end to end.
    cfg = config_from_hf(path).replace(dtype="float32", quantization=quant,
                                       quant_group_size=32)
    full = load_weights(path, cfg)                       # host stack + upload
    mesh = make_mesh(**mesh_kw)
    shardings, _ = resolve_shardings(mesh, cfg)
    streamed = load_weights(path, cfg, shardings=shardings)
    _trees_equal(full, streamed)


@pytest.mark.parametrize("quant", [None, "int8", "int4"])
def test_streamed_moe_matches_full(tmp_path, quant):
    path = _ckpt_dir(tmp_path, moe=True)
    cfg = config_from_hf(path).replace(dtype="float32", quantization=quant,
                                       quant_group_size=32)
    full = load_weights(path, cfg)
    mesh = make_mesh(ep=2, tp=2)
    shardings, _ = resolve_shardings(mesh, cfg)
    streamed = load_weights(path, cfg, shardings=shardings)
    _trees_equal(full, streamed)


def test_streamed_multifile_checkpoint(tmp_path):
    """Ranged reads across a checkpoint split into multiple safetensors
    files (the HF sharded-save layout every big model uses)."""
    path = _ckpt_dir(tmp_path, shards="40KB")
    files = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    assert len(files) > 1, files
    cfg = config_from_hf(path).replace(dtype="float32")
    full = load_weights(path, cfg)
    mesh = make_mesh(tp=2)
    shardings, _ = resolve_shardings(mesh, cfg)
    _trees_equal(full, load_weights(path, cfg, shardings=shardings))


# ---------------------------------------------------------------------------
# 2-process RSS proof
# ---------------------------------------------------------------------------

RSS_WORKER = r"""
import os, sys, tracemalloc
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["KGCT_REPO"])
from kubernetes_gpu_cluster_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed()
assert jax.process_count() == 2 and jax.local_device_count() == 1

import jax.numpy as jnp
from kubernetes_gpu_cluster_tpu.engine.engine import resolve_shardings
from kubernetes_gpu_cluster_tpu.engine.weights import config_from_hf, load_weights

path = os.environ["KGCT_CKPT"]
cfg = config_from_hf(path).replace(dtype="float32")
mesh = make_mesh(pp=2)
shardings, _ = resolve_shardings(mesh, cfg)
tracemalloc.start()
params = load_weights(path, cfg, shardings=shardings)
peak = tracemalloc.get_traced_memory()[1]
tracemalloc.stop()

global_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
local_bytes = sum(
    sum(s.data.size * s.data.dtype.itemsize for s in x.addressable_shards)
    for x in jax.tree.leaves(params))
rank = jax.process_index()
print(f"RANK{rank}-STATS peak={peak} global={global_bytes} local={local_bytes}",
      flush=True)
"""


@pytest.mark.skipif(sys.platform != "linux", reason="localhost gloo test")
def test_two_process_streamed_rss(tmp_path):
    """Each process of a pp=2 mesh loads a multi-file checkpoint: its numpy
    peak must stay well under the full model bytes (the old loader stacked
    the whole model host-side in every process), and its resident shards
    must be ~half the layer stack."""
    from transformers import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=1024,
        num_hidden_layers=8, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=256)
    torch.manual_seed(2)
    model = LlamaForCausalLM(cfg).eval()
    ckpt = tmp_path / "big"
    model.save_pretrained(ckpt, safe_serialization=True, max_shard_size="5MB")
    assert len([f for f in os.listdir(ckpt)
                if f.endswith(".safetensors")]) > 1

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(RSS_WORKER)
    repo = str(pathlib.Path(__file__).resolve().parent.parent)

    procs = []
    for rank in (0, 1):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update({
            "KGCT_REPO": repo, "KGCT_CKPT": str(ckpt),
            "KGCT_COORDINATOR": f"127.0.0.1:{port}",
            "KGCT_NUM_PROCESSES": "2", "KGCT_PROCESS_ID": str(rank),
            "JAX_NUM_CPU_DEVICES": "1", "TPU_SKIP_MDS_QUERY": "1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{err[-3000:]}"
        stats = dict(
            kv.split("=") for kv in
            next(l for l in out.splitlines()
                 if l.startswith(f"RANK{rank}-STATS")).split()[1:])
        peak, g, local = (int(stats["peak"]), int(stats["global"]),
                          int(stats["local"]))
        # The old loader's numpy peak was >= the full model (~g). Streamed:
        # bounded by this rank's shards + one transient layer slice.
        assert peak < 0.7 * g, (peak, g)
        # pp=2: half the layer stack + replicated embed/head.
        assert local < 0.75 * g, (local, g)


# ---------------------------------------------------------------------------
# 70B load plan
# ---------------------------------------------------------------------------

def load_plan(cfg, mesh_shape: dict, hosts: int, dtype_bytes: int = 2) -> dict:
    """Worst-case per-host bytes for a streamed load: every param's bytes
    divided by the product of its sharded axes, times the host's device
    count (each device may hold a distinct shard), capped at param bytes."""
    from kubernetes_gpu_cluster_tpu.parallel.pp import param_pp_specs

    world = 1
    for v in mesh_shape.values():
        world *= v
    dev_per_host = world // hosts
    specs = param_pp_specs(cfg)

    d, L = cfg.hidden_size, cfg.num_layers
    nh, nkv, hd, ff = (cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                       cfg.intermediate_size)
    V = cfg.vocab_size
    wb = 1 if cfg.quantization == "int8" else dtype_bytes
    shapes = {
        "embed": ((V, d), dtype_bytes), "final_norm": ((d,), dtype_bytes),
        "lm_head": ((d, V), wb),
        "layers": {
            "input_norm": ((L, d), dtype_bytes),
            "post_attn_norm": ((L, d), dtype_bytes),
            "wq": ((L, d, nh * hd), wb), "wk": ((L, d, nkv * hd), wb),
            "wv": ((L, d, nkv * hd), wb), "wo": ((L, nh * hd, d), wb),
            "w_gate": ((L, d, ff), wb), "w_up": ((L, d, ff), wb),
            "w_down": ((L, ff, d), wb),
        },
    }
    per_host = 0
    for group, entry in shapes.items():
        items = entry.items() if isinstance(entry, dict) else [(group, entry)]
        for name, (shape, b) in items:
            spec = (specs["layers"] if isinstance(entry, dict)
                    else specs).get(name)
            n_shards = 1
            for axes in (spec or ()):
                for ax in ([axes] if isinstance(axes, str) else (axes or ())):
                    n_shards *= mesh_shape.get(ax, 1)
            total = int(np.prod(shape)) * b
            per_host += min(total,
                            (total // n_shards) * min(dev_per_host, n_shards))
    # Transient: one full [out, in] layer row-block (the row-quantization
    # scale read) in f32.
    transient = max(nh * hd * d, ff * d) * 4
    return {"per_host_bytes": per_host, "transient_bytes": transient}


def test_70b_load_plan_under_40gb():
    """BASELINE config 5: llama-3-70b on a v5p-64 (16 hosts x 4 chips),
    pp=8 x tp=8. Per-host streamed-load RSS must be far under 40 GB (the
    old full-stack loader needed ~140 GB per host)."""
    cfg = get_model_config("llama-3-70b")
    plan = load_plan(cfg, {"pp": 8, "tp": 8}, hosts=16)
    total = plan["per_host_bytes"] + plan["transient_bytes"]
    assert total < 40e9, plan
    # and the bf16 whole model really is ~140 GB, so the plan is a >3x win
    assert total < 141e9 / 3
