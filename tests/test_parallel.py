"""Sharding correctness on the 8-device virtual CPU mesh.

Strategy (SURVEY §4: the fake-backend testing the reference lacked): every
parallel path must produce the same numbers as the single-device oracle —
TP/EP via GSPMD annotations, EP via manual shard_map, PP via the circular
pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import EngineConfig, get_model_config
from kubernetes_gpu_cluster_tpu.engine.engine import LLMEngine
from kubernetes_gpu_cluster_tpu.engine.sampling_params import SamplingParams
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.parallel import make_mesh, param_shardings
from kubernetes_gpu_cluster_tpu.parallel.ep import moe_block_ep
from kubernetes_gpu_cluster_tpu.parallel.pp import build_pp_forward, pp_logits
from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
from kubernetes_gpu_cluster_tpu.config.engine_config import CacheConfig


def _greedy_engine(name, mesh=None, **overrides):
    cfg = EngineConfig.from_model_name(name, **overrides)
    return LLMEngine(cfg, mesh=mesh, eos_token_id=None)


PROMPTS = [[1, 5, 9, 2], [3, 3, 7], [11, 4, 8, 6, 2, 10]]
GREEDY = SamplingParams(temperature=0.0, max_tokens=8)


def _generate_tokens(engine):
    outs = engine.generate(PROMPTS, GREEDY)
    return [o.output_token_ids for o in outs]


class TestTensorParallel:
    def test_tp_matches_single_device(self):
        """Same params served on a 1-device engine and a tp=4 mesh engine must
        greedy-decode identical tokens."""
        cfg = EngineConfig.from_model_name("debug-tiny")
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        ref = LLMEngine(cfg, params=params)
        ref_tokens = _generate_tokens(ref)

        mesh = make_mesh(tp=4, dp=2)
        tp = LLMEngine(cfg, params=params, mesh=mesh)
        tp_tokens = _generate_tokens(tp)
        assert ref_tokens == tp_tokens

    def test_tp_param_shardings_cover_params(self):
        cfg = get_model_config("debug-moe")
        mesh = make_mesh(tp=2, ep=2, dp=2)
        params = model_lib.init_params(cfg, jax.random.key(0))
        shardings = param_shardings(mesh, cfg)
        # Structures must match exactly (device_put would fail otherwise).
        jax.tree.map(lambda a, s: None, params, shardings)

    def test_pp_engine_matches_single_device(self):
        """VERDICT r3 missing #1: pp must be a SERVING capability, not a
        library module. Same params through the full LLMEngine on a
        pp=2 x tp=2 x dp=2 mesh must greedy-decode identical tokens to the
        single-device engine (reference served pipelineParallelSize: 2,
        values-01-minimal-example4.yaml:16-23)."""
        cfg = EngineConfig.from_model_name("debug-tiny")
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        ref_tokens = _generate_tokens(LLMEngine(cfg, params=params))

        mesh = make_mesh(pp=2, tp=2, dp=2)
        eng = LLMEngine(cfg, params=params, mesh=mesh)
        assert eng.pp_size == 2
        assert _generate_tokens(eng) == ref_tokens

    def test_pp_only_mesh_matches_single_device(self):
        """pp=2 with no tp: microbatched decode (M=2) over the layer-split
        stages alone."""
        cfg = EngineConfig.from_model_name("debug-tiny")
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        ref_tokens = _generate_tokens(LLMEngine(cfg, params=params))
        eng = LLMEngine(cfg, params=params, mesh=make_mesh(pp=2))
        assert _generate_tokens(eng) == ref_tokens

    def test_pp_engine_chunked_prefill(self):
        """Prompts longer than max_prefill_tokens take the chunked-prefill
        history path, which under pp runs as plain GSPMD over the pp-sharded
        params (no pipelined variant) — lock in token parity so a regression
        there can't ship unseen."""
        long_prompt = [((7 * i) % 500) + 1 for i in range(40)]
        from kubernetes_gpu_cluster_tpu.config import SchedulerConfig
        cfg = EngineConfig.from_model_name(
            "debug-tiny", scheduler=SchedulerConfig(
                max_prefill_tokens=16, prefill_buckets=(16,)))
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        ref = LLMEngine(cfg, params=params).generate([long_prompt], GREEDY)
        eng = LLMEngine(cfg, params=params, mesh=make_mesh(pp=2, tp=2, dp=2))
        out = eng.generate([long_prompt], GREEDY)
        assert out[0].output_token_ids == ref[0].output_token_ids

    def test_pp_engine_rejects_indivisible_layers(self):
        """A 2-layer model cannot split into 8 stages; the engine must refuse
        at init (not silently replicate, the round-3 failure mode)."""
        cfg = EngineConfig.from_model_name("debug-tiny")
        with pytest.raises(ValueError, match="num_layers"):
            LLMEngine(cfg, mesh=make_mesh(pp=8))

    def test_sp_engine_matches_single_device(self):
        """Ring attention as a SERVING capability: the engine on an sp=4 x
        dp=2 mesh routes prefill attention through the sp ring and must
        greedy-decode identical tokens to the single-device engine."""
        cfg = EngineConfig.from_model_name("debug-tiny")
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        ref_tokens = _generate_tokens(LLMEngine(cfg, params=params))
        eng = LLMEngine(cfg, params=params, mesh=make_mesh(sp=4, dp=2))
        assert eng.sp_size == 4
        assert _generate_tokens(eng) == ref_tokens

    def test_sp_engine_rejects_indivisible_buckets(self):
        from kubernetes_gpu_cluster_tpu.config import SchedulerConfig
        cfg = EngineConfig.from_model_name(
            "debug-tiny", scheduler=SchedulerConfig(prefill_buckets=(100,)))
        with pytest.raises(ValueError, match="prefill buckets"):
            LLMEngine(cfg, mesh=make_mesh(sp=8))

    def test_sp_engine_rejects_pp_combination(self):
        cfg = EngineConfig.from_model_name("debug-tiny")
        with pytest.raises(ValueError, match="sp and pp"):
            LLMEngine(cfg, mesh=make_mesh(sp=2, pp=2))

    def test_tp_rejects_indivisible_heads(self):
        cfg = get_model_config("debug-tiny")  # 4 heads
        mesh = make_mesh(tp=8)
        with pytest.raises(ValueError, match="not divisible"):
            param_shardings(mesh, cfg)


class TestExpertParallel:
    def test_moe_ep_matches_single_device(self):
        """MoE engine on an ep=2 x tp=2 mesh must match the 1-device engine."""
        cfg = EngineConfig.from_model_name("debug-moe")
        params = model_lib.init_params(cfg.model, jax.random.key(1))
        ref = LLMEngine(cfg, params=params)
        ref_tokens = _generate_tokens(ref)

        mesh = make_mesh(tp=2, ep=2, dp=2)
        ep = LLMEngine(cfg, params=params, mesh=mesh)
        ep_tokens = _generate_tokens(ep)
        assert ref_tokens == ep_tokens

    def test_moe_block_shard_map_matches_dense(self):
        cfg = get_model_config("debug-moe")
        key = jax.random.key(2)
        params = model_lib.init_params(cfg, key)
        lp = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0
        layer = {k: lp[k] for k in ("router", "w_gate", "w_up", "w_down")}
        x = jax.random.normal(jax.random.key(3), (6, cfg.hidden_size), jnp.float32)

        dense = model_lib._moe_mlp(layer, x, cfg)
        mesh = make_mesh(tp=2, ep=2, dp=2)
        ep_out = moe_block_ep(mesh, cfg, layer, x)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(ep_out),
                                   rtol=2e-5, atol=2e-5)


class TestPipelineParallel:
    def _setup(self, name="debug-tiny", pp=2, tp=1):
        cfg = get_model_config(name)
        mesh = make_mesh(pp=pp, tp=tp, dp=8 // (pp * tp))
        params = model_lib.init_params(cfg, jax.random.key(4))
        cache_cfg = CacheConfig(page_size=8, num_pages=17)
        kv = allocate_kv_cache(cfg, cache_cfg, 17)
        return cfg, mesh, params, kv, cache_cfg

    def _prefill_meta(self, M, T, page0):
        """M single-sequence microbatches of T tokens each; each microbatch's
        pages start at page0[m]."""
        seg_ids = np.zeros((M, T), np.int32)
        positions = np.tile(np.arange(T, dtype=np.int32), (M, 1))
        slot = np.stack([page0[m] * 8 + np.arange(T, dtype=np.int32)
                         for m in range(M)])
        logits_idx = np.full((M, 1), T - 1, np.int32)
        return model_lib.PrefillMeta(
            seg_ids=jnp.asarray(seg_ids), positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slot), logits_indices=jnp.asarray(logits_idx))

    def test_pp_prefill_matches_single_device(self):
        cfg, mesh, params, kv, cache_cfg = self._setup(pp=2, tp=2)
        M, T = 3, 8
        tokens = np.array([[1, 5, 9, 2, 7, 3, 4, 6],
                           [3, 3, 7, 1, 2, 8, 5, 9],
                           [11, 4, 8, 6, 2, 10, 1, 5]], np.int32)
        page0 = np.array([1, 2, 3])  # page 0 is scrap
        meta_mb = self._prefill_meta(M, T, page0)

        # Oracle: run each microbatch through the unsharded model.
        kv_ref = allocate_kv_cache(cfg, cache_cfg, 17)
        ref_logits = []
        for m in range(M):
            meta = jax.tree.map(lambda a: a[m], meta_mb)
            normed, kv_ref, _ = model_lib.forward_prefill(
                params, cfg, jnp.asarray(tokens[m]), meta, kv_ref)
            ref_logits.append(model_lib.compute_logits(params, cfg, normed))

        pp_fn = build_pp_forward(mesh, cfg, "prefill")
        hidden_mb, kv_pp = pp_fn(params, kv, jnp.asarray(tokens), meta_mb)
        for m in range(M):
            got = pp_logits(params, cfg, hidden_mb[m],
                            logits_indices=meta_mb.logits_indices[m])
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref_logits[m]),
                                       rtol=2e-4, atol=2e-4)
        # KV pools must match too (PP writes the same pages, layer-sharded).
        # Page 0 is the scrap page: the pipeline's masked inactive ticks dump
        # garbage there by design, so it is excluded.
        np.testing.assert_allclose(np.asarray(kv_pp.k)[:, 1:],
                                   np.asarray(kv_ref.k)[:, 1:],
                                   rtol=2e-4, atol=2e-4)

    def test_pp_decode_matches_single_device(self):
        cfg, mesh, params, kv, cache_cfg = self._setup(pp=2, tp=1)
        M, B = 2, 2
        # Pretend each sequence has 3 tokens of context already; decode token 4.
        rng = np.random.default_rng(0)
        kv_np_k = rng.standard_normal(np.shape(kv.k)).astype(np.float32) * 0.02
        kv_np_v = rng.standard_normal(np.shape(kv.v)).astype(np.float32) * 0.02
        from kubernetes_gpu_cluster_tpu.engine.kv_cache import KVCache
        kv = KVCache(k=jnp.asarray(kv_np_k), v=jnp.asarray(kv_np_v))
        kv_ref = KVCache(k=jnp.asarray(kv_np_k), v=jnp.asarray(kv_np_v))

        tokens = np.array([[7, 9], [2, 4]], np.int32)           # [M, B]
        positions = np.full((M, B), 3, np.int32)
        # seq (m, b) owns page 1 + 2*m + b
        pages = 1 + 2 * np.arange(M)[:, None] + np.arange(B)[None, :]
        slot = (pages * 8 + 3).astype(np.int32)
        page_tables = pages[..., None].astype(np.int32)          # [M, B, 1]
        context_lens = np.full((M, B), 4, np.int32)
        meta_mb = model_lib.DecodeMeta(
            positions=jnp.asarray(positions), slot_mapping=jnp.asarray(slot),
            page_tables=jnp.asarray(page_tables),
            context_lens=jnp.asarray(context_lens))

        ref_logits = []
        for m in range(M):
            meta = jax.tree.map(lambda a: a[m], meta_mb)
            normed, kv_ref, _ = model_lib.forward_decode(
                params, cfg, jnp.asarray(tokens[m]), meta, kv_ref)
            ref_logits.append(model_lib.compute_logits(params, cfg, normed))

        pp_fn = build_pp_forward(mesh, cfg, "decode")
        hidden_mb, kv_pp = pp_fn(params, kv, jnp.asarray(tokens), meta_mb)
        for m in range(M):
            got = pp_logits(params, cfg, hidden_mb[m])
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref_logits[m]),
                                       rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(kv_pp.k)[:, 1:],
                                   np.asarray(kv_ref.k)[:, 1:],
                                   rtol=2e-4, atol=2e-4)

    def test_pp_rejects_indivisible_layers(self):
        cfg = get_model_config("debug-tiny").replace(num_layers=3)
        mesh = make_mesh(pp=2, dp=4)
        with pytest.raises(ValueError, match="not divisible"):
            build_pp_forward(mesh, cfg, "decode")


def test_parallel_config_sp_axis():
    """--sequence-parallel-size reaches the engine: ParallelConfig carries sp
    and mesh_from_config builds the sp mesh (serving-config reachability)."""
    from kubernetes_gpu_cluster_tpu.config.engine_config import ParallelConfig
    from kubernetes_gpu_cluster_tpu.parallel import mesh_from_config

    cfg = ParallelConfig(sp=8)
    assert cfg.world_size == 8
    mesh = mesh_from_config(cfg)
    assert mesh.shape["sp"] == 8
    assert mesh_from_config(ParallelConfig()) is None


@pytest.mark.parametrize("model,axes", [
    ("llama-3-8b", {"tp": 8}),            # BASELINE config 3: 8B TP=8 over ICI
    ("mixtral-8x7b", {"tp": 2, "ep": 4}),  # config 4: MoE expert-parallel
    ("llama-3-70b", {"tp": 8}),           # config 5 (TP part): 70B one slice
])
def test_north_star_configs_trace(model, axes):
    """BASELINE north-star configs at FULL model geometry: the sharded decode
    step must TRACE cleanly — params as ShapeDtypeStructs, so no weights
    materialize — proving shapes, sharding specs, and kernel lane math are
    sound at scales the single-chip driver cannot execute."""
    import jax

    from kubernetes_gpu_cluster_tpu.config import get_model_config
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import KVCache
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh
    from kubernetes_gpu_cluster_tpu.parallel.sharding import (
        kv_cache_sharding, param_shardings)

    cfg = get_model_config(model)
    mesh = make_mesh(**axes)
    shardings = param_shardings(mesh, cfg)   # validates divisibility
    p_shapes = jax.eval_shape(lambda: model_lib.init_params(cfg, jax.random.key(0)))
    # Structures must match so device_put(params, shardings) would succeed.
    jax.tree.map(lambda a, s: None, p_shapes, shardings)
    assert kv_cache_sharding(mesh, cfg) is not None

    B, pps, ps = 4, 4, 16
    kv_shape = (cfg.num_layers, 1 + B * pps, ps,
                cfg.num_kv_heads * cfg.head_dim)
    kv = KVCache(k=jax.ShapeDtypeStruct(kv_shape, cfg.jnp_dtype),
                 v=jax.ShapeDtypeStruct(kv_shape, cfg.jnp_dtype))
    meta = model_lib.DecodeMeta(
        positions=jax.ShapeDtypeStruct((B,), jnp.int32),
        slot_mapping=jax.ShapeDtypeStruct((B,), jnp.int32),
        page_tables=jax.ShapeDtypeStruct((B, pps), jnp.int32),
        context_lens=jax.ShapeDtypeStruct((B,), jnp.int32))
    tokens = jax.ShapeDtypeStruct((B,), jnp.int32)

    def step(params, kv, tokens, meta):
        hidden, kv, _ = model_lib.forward_decode(params, cfg, tokens, meta, kv)
        return model_lib.compute_logits(params, cfg, hidden), kv

    out_shape = jax.eval_shape(step, p_shapes, kv, tokens, meta)
    assert out_shape[0].shape == (B, cfg.vocab_size)


def test_north_star_70b_tp_pp_traces():
    """Config 5's TP+PP form: the circular-pipeline decode forward traces at
    full 70B geometry over pp=2 x tp=4 (80 layers -> 40-layer stages)."""
    import jax

    from kubernetes_gpu_cluster_tpu.config import get_model_config
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import KVCache
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh
    from kubernetes_gpu_cluster_tpu.parallel.pp import (build_pp_forward,
                                                        validate_pp_mesh)

    cfg = get_model_config("llama-3-70b")
    mesh = make_mesh(pp=2, tp=4)
    validate_pp_mesh(mesh, cfg)
    p_shapes = jax.eval_shape(lambda: model_lib.init_params(cfg, jax.random.key(0)))

    M, B, pps, ps = 2, 2, 4, 16
    kv_shape = (cfg.num_layers, 1 + M * B * pps, ps,
                cfg.num_kv_heads * cfg.head_dim)
    kv = KVCache(k=jax.ShapeDtypeStruct(kv_shape, cfg.jnp_dtype),
                 v=jax.ShapeDtypeStruct(kv_shape, cfg.jnp_dtype))
    meta = model_lib.DecodeMeta(
        positions=jax.ShapeDtypeStruct((M, B), jnp.int32),
        slot_mapping=jax.ShapeDtypeStruct((M, B), jnp.int32),
        page_tables=jax.ShapeDtypeStruct((M, B, pps), jnp.int32),
        context_lens=jax.ShapeDtypeStruct((M, B), jnp.int32))
    tokens = jax.ShapeDtypeStruct((M, B), jnp.int32)

    fn = build_pp_forward(mesh, cfg, "decode", use_pallas=False)
    out_shape, kv_shape_out = jax.eval_shape(fn, p_shapes, kv, tokens, meta)
    assert out_shape.shape == (M, B, cfg.hidden_size)


def test_pp_hist_no_layer_stack_gather():
    """The pipelined chunked-prefill program must keep the layer stack
    pp-sharded: its compiled HLO contains NO all-gather reassembling a full
    stacked weight (VERDICT r4 #6 — the old GSPMD path gathered the stack on
    every long-prompt chunk)."""
    from kubernetes_gpu_cluster_tpu.models.llama import PrefillMeta
    from kubernetes_gpu_cluster_tpu.parallel.pp import (
        build_pp_mapped, pp_kv_sharding, pp_param_shardings)

    cfg = get_model_config("debug-tiny")
    mesh = make_mesh(pp=2)
    mapped = build_pp_mapped(mesh, cfg, "prefill_hist", use_pallas=False)
    params = jax.device_put(model_lib.init_params(cfg, jax.random.key(0)),
                            pp_param_shardings(mesh, cfg))
    kv = allocate_kv_cache(cfg, CacheConfig(page_size=8, num_pages=16), 16,
                           pp_kv_sharding(mesh))
    M, sub = 2, 8
    meta_mb = PrefillMeta(
        seg_ids=jnp.zeros((M, sub), jnp.int32),
        positions=jnp.tile(jnp.arange(sub, dtype=jnp.int32), (M, 1)),
        slot_mapping=jnp.zeros((M, sub), jnp.int32),
        logits_indices=jnp.zeros((M, 1), jnp.int32))
    f = jax.jit(mapped)
    txt = f.lower(params, kv.k, kv.v, jnp.zeros((M, sub), jnp.int32),
                  meta_mb, jnp.zeros((4,), jnp.int32),
                  jnp.zeros((M,), jnp.int32)).compile().as_text()
    L, d = cfg.num_layers, cfg.hidden_size
    stacked_marker = f"[{L},{d},"   # any full [L, d, *] weight reassembly
    offending = [ln for ln in txt.splitlines()
                 if "all-gather" in ln and stacked_marker in ln]
    assert not offending, offending[:3]


def test_sampled_tail_features_under_mesh():
    """Seeded sampling, penalties, and logit_bias must work UNDER a GSPMD
    mesh (the sampled decode program's counts/out_tokens/bias buffers ride
    pjit like any other input) and reproduce the single-device outputs —
    seeded rows are batch/mesh-invariant by construction."""
    cfg = EngineConfig.from_model_name("debug-tiny")
    params = model_lib.init_params(cfg.model, jax.random.key(0))
    sp = [SamplingParams(max_tokens=10, temperature=0.8, seed=5,
                         frequency_penalty=1.0, presence_penalty=0.5),
          SamplingParams(max_tokens=10, temperature=0.0,
                         logit_bias={7: 100.0})]
    prompts = [[3, 1, 4], [2, 7, 1]]
    ref = LLMEngine(cfg, params=params).generate(prompts, sp)
    mesh_eng = LLMEngine(cfg, params=params, mesh=make_mesh(tp=4, dp=2))
    got = mesh_eng.generate(prompts, sp)
    assert got[1].output_token_ids == [7] * 10
    for a, b in zip(ref, got):
        assert a.output_token_ids == b.output_token_ids
