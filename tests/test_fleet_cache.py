"""Fleet-wide KV reuse: the global prefix cache over the handoff substrate.

Tier-1 keeps the CHEAP pins: one module-scoped debug-tiny engine PAIR
proves the acceptance contract — a prefix pulled from a peer's cache and
streamed into the local cache yields BYTE-IDENTICAL output to recomputing
it (greedy AND seeded) — plus engine-free codec/policy/queue pins and ONE
two-server HTTP scenario (pull ok / roofline skip / allowlist /
kv_pull_fail chaos) on the same tiny engines. Full-topology soaks through
the router belong to the bench phase (KGCT_BENCH_FLEET_CACHE).
"""

import asyncio

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
from kubernetes_gpu_cluster_tpu.serving.fleet_cache import (
    PullPolicy, SpillQueue, build_pull_policy, kv_bytes_per_token,
    prefill_flops_per_token)
from kubernetes_gpu_cluster_tpu.serving.handoff import (
    PrefixStreamDecoder, decode_spill_frame, encode_prefix_frames,
    encode_spill_frame)


@pytest.fixture(autouse=True)
def _clean_faults():
    configure_faults(None)
    yield
    configure_faults(None)


def _engine_config(swap_gb: float = 0.0):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=96,
                          swap_space_gb=swap_gb),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=128,
                                  decode_buckets=(1, 2),
                                  prefill_buckets=(32, 64, 128),
                                  decode_window=4, mixed_batch_enabled=False,
                                  enable_prefix_caching=True))


@pytest.fixture(scope="module")
def engines():
    """(owner, importer): identical weights by construction (same seed).
    The importer carries a host tier so the remote-spill rung is
    exercisable on the same pair."""
    return LLMEngine(_engine_config()), LLMEngine(_engine_config(0.001))


PROMPT = np.random.default_rng(3).integers(1, 500, 80).tolist()


def _stream_import(dst: LLMEngine, state: dict, chunk_pages: int = 2) -> int:
    """Wire round-trip + streamed import: encode the export as the actual
    prefix frames, feed them through the incremental decoder, and scatter
    each chunk through the begin/chunk/commit seam."""
    dec = PrefixStreamDecoder()
    handle = None
    for part in encode_prefix_frames(state, chunk_pages=chunk_pages):
        chunks = dec.feed(bytes(part))
        if handle is None and dec.header is not None:
            handle = dst.begin_prefix_import(dict(dec.header))
        for ck, cv in chunks:
            dst.import_prefix_chunk(handle, ck, cv)
    assert dec.done
    return dst.commit_prefix_import(handle)


class TestPullPolicy:
    """Engine-free pins of the anti-thrash roofline gate."""

    def _policy(self, link=1e9, flops=1e9, kvb=1000.0, fpt=1000.0, mn=16):
        return PullPolicy(link_bytes_per_s=link, flops_per_s=flops,
                          kv_bytes_per_token=kvb, flops_per_token=fpt,
                          min_tokens=mn)

    def test_fast_link_slow_compute_pulls(self):
        # transfer: 1 KB/tok over 1 GB/s = 1 us/tok; recompute: 1 kFLOP
        # over 1 MFLOP/s = 1 ms/tok -> pull wins.
        p = self._policy(link=1e9, flops=1e6)
        assert p.pull_beats_recompute(64)

    def test_slow_link_fast_compute_skips(self):
        # transfer: 1 KB/tok over 1 KB/s = 1 s/tok; recompute: 1 kFLOP
        # over 1 GFLOP/s = 1 us/tok -> the gate refuses the pull.
        p = self._policy(link=1e3, flops=1e9)
        assert not p.pull_beats_recompute(64)

    def test_sub_page_matches_never_pull(self):
        p = self._policy(link=1e12, flops=1.0, mn=16)
        assert not p.pull_beats_recompute(15)
        assert p.pull_beats_recompute(16)

    def test_build_policy_mirrors_roofline_accounting(self):
        mcfg = get_model_config("debug-tiny")
        pol = build_pull_policy(mcfg, page_size=16, itemsize=4,
                                backend="cpu")
        assert pol.kv_bytes_per_token == kv_bytes_per_token(mcfg, 4)
        assert pol.flops_per_token == prefill_flops_per_token(mcfg)
        assert pol.min_tokens == 16
        # The FLOPs model is bench.py's prefill matmul term: 2 FLOPs/MAC
        # over attention projections + MLP, every layer.
        h, inter = mcfg.hidden_size, mcfg.intermediate_size
        attn = (h * mcfg.num_heads * mcfg.head_dim
                + 2 * h * mcfg.num_kv_heads * mcfg.head_dim
                + mcfg.num_heads * mcfg.head_dim * h)
        assert pol.flops_per_token == 2 * mcfg.num_layers * (
            attn + 3 * h * inter)


class TestPrefixStreamCodec:
    """Engine-free pins of the streamed wire format (serving/handoff.py)."""

    def _state(self, n_pages=5, dtype="float32"):
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, n_pages, 16, 64)).astype(dtype)
        return {"model": "debug-tiny", "page_size": 16, "dtype": dtype,
                "matched_tokens": n_pages * 16,
                "prompt_token_ids": list(range(n_pages * 16)),
                "k": k, "v": k + 1}

    def test_roundtrip_across_dribbled_feeds(self):
        """Chunks must come out correct however the bytes are sliced on
        the wire — feed the frame one 1000-byte dribble at a time."""
        state = self._state()
        blob = b"".join(bytes(p) for p in
                        encode_prefix_frames(state, chunk_pages=2))
        dec = PrefixStreamDecoder()
        got = []
        for i in range(0, len(blob), 1000):
            got.extend(dec.feed(blob[i:i + 1000]))
        assert dec.done and dec.header["matched_tokens"] == 80
        k = np.concatenate([ck for ck, _ in got], axis=1)
        v = np.concatenate([cv for _, cv in got], axis=1)
        np.testing.assert_array_equal(k, state["k"])
        np.testing.assert_array_equal(v, state["v"])
        # chunk sizes: 2 + 2 + 1 (last chunk short)
        assert [ck.shape[1] for ck, _ in got] == [2, 2, 1]

    def test_corrupt_frames_rejected(self):
        blob = b"".join(bytes(p) for p in
                        encode_prefix_frames(self._state()))
        with pytest.raises(ValueError, match="magic"):
            PrefixStreamDecoder().feed(b"NOTAPF1!" + blob[8:])
        with pytest.raises(ValueError, match="trailing"):
            PrefixStreamDecoder().feed(blob + b"x")
        dec = PrefixStreamDecoder()
        dec.feed(blob[:-5])
        assert not dec.done      # truncated: never silently complete

    def test_spill_frame_roundtrip(self):
        rng = np.random.default_rng(1)
        k = rng.standard_normal((2, 1, 16, 64)).astype(np.float32)
        blob = encode_spill_frame("ab" * 16, k, k + 2, "debug-tiny", 16)
        digest, header, k2, v2 = decode_spill_frame(blob)
        assert digest == "ab" * 16
        assert header["model"] == "debug-tiny"
        np.testing.assert_array_equal(k2, k)
        np.testing.assert_array_equal(v2, k + 2)
        with pytest.raises(ValueError):
            decode_spill_frame(blob[:-3])


class TestSpillQueue:
    def test_bounded_drop_oldest(self):
        q = SpillQueue(cap=2)
        assert q.offer("a", None, None)
        assert q.offer("b", None, None)
        assert not q.offer("c", None, None)   # displaced the oldest
        assert q.dropped == 1
        assert q.pop()[0] == "b"
        assert q.pop()[0] == "c"
        assert q.pop() is None


class TestPulledPrefixByteIdentity:
    """The acceptance contract, engine-level: export from the owner's
    cache -> actual wire frames -> streamed import -> the importer's own
    admission reuses the pages — output byte-identical to recomputing."""

    def test_greedy_identical_and_cache_hit(self, engines):
        owner, importer = engines
        params = SamplingParams(max_tokens=8, temperature=0.0)
        ref = owner.generate([PROMPT], params)[0].output_token_ids
        hits0, misses0 = (owner.scheduler.prefix_cache.hits,
                          owner.scheduler.prefix_cache.misses)
        state = owner.export_prefix(PROMPT)
        # Serving a peer's fetch must not skew the owner's own locality
        # stats (the router's per-replica hit-ratio gauge reads them).
        assert (owner.scheduler.prefix_cache.hits,
                owner.scheduler.prefix_cache.misses) == (hits0, misses0)
        assert state["matched_tokens"] == 64      # 80 tokens, 16/page, <80
        tokens = _stream_import(importer, state)
        assert tokens == 64
        assert importer.prefix_peek(PROMPT) == 64
        hits_before = importer.scheduler.prefix_cache.hits
        got = importer.generate([PROMPT], params)[0].output_token_ids
        assert got == ref
        assert importer.scheduler.prefix_cache.hits == hits_before + 1

    def test_seeded_sampled_identical(self, engines):
        owner, importer = engines
        params = SamplingParams(max_tokens=8, temperature=0.9, top_k=30,
                                top_p=0.95, seed=17)
        ref = owner.generate([PROMPT], params)[0].output_token_ids
        got = importer.generate([PROMPT], params)[0].output_token_ids
        assert got == ref

    def test_truncated_import_raises_and_frees(self, engines):
        owner, importer = engines
        state = owner.export_prefix(PROMPT)
        free0 = importer.scheduler.allocator.num_free
        handle = importer.begin_prefix_import(
            {k: v for k, v in state.items() if k not in ("k", "v")})
        importer.import_prefix_chunk(handle, state["k"][:, :2],
                                     state["v"][:, :2])
        with pytest.raises(ValueError, match="truncated"):
            importer.commit_prefix_import(handle)
        assert importer.scheduler.allocator.num_free == free0

    def test_abort_import_frees(self, engines):
        owner, importer = engines
        state = owner.export_prefix(PROMPT)
        free0 = importer.scheduler.allocator.num_free
        handle = importer.begin_prefix_import(
            {k: v for k, v in state.items() if k not in ("k", "v")})
        assert importer.scheduler.allocator.num_free < free0
        importer.abort_prefix_import(handle)
        importer.abort_prefix_import(handle)      # idempotent
        assert importer.scheduler.allocator.num_free == free0

    def test_mismatched_header_rejected_without_pages(self, engines):
        owner, importer = engines
        state = owner.export_prefix(PROMPT)
        free0 = importer.scheduler.allocator.num_free
        hdr = {k: v for k, v in state.items() if k not in ("k", "v")}
        for field, garbage in (("model", "llama-3-8b"), ("page_size", 32),
                               ("dtype", "float16"),
                               ("matched_tokens", 63)):
            with pytest.raises(ValueError):
                importer.begin_prefix_import(dict(hdr, **{field: garbage}))
            assert importer.scheduler.allocator.num_free == free0

    def test_mismatched_chunk_aborts_the_import(self, engines):
        owner, importer = engines
        state = owner.export_prefix(PROMPT)
        free0 = importer.scheduler.allocator.num_free
        handle = importer.begin_prefix_import(
            {k: v for k, v in state.items() if k not in ("k", "v")})
        bad = state["k"][:, :1].astype(np.float16)
        with pytest.raises(ValueError):
            importer.import_prefix_chunk(handle, bad, bad)
        # The failed chunk aborted the whole import: pages back, handle
        # dead.
        assert importer.scheduler.allocator.num_free == free0
        with pytest.raises(ValueError, match="unknown"):
            importer.commit_prefix_import(handle)


class TestDeltaExport:
    """The fetch ships only the DELTA beyond the puller's local coverage
    (the span the roofline gate priced), and the offset import registers
    a tail chain that becomes reachable once its head arrives."""

    P2 = np.random.default_rng(21).integers(1, 500, 80).tolist()

    def test_delta_then_head_compose(self, engines):
        owner, importer = engines
        params = SamplingParams(max_tokens=6, temperature=0.0)
        ref = owner.generate([self.P2], params)[0].output_token_ids
        delta = owner.export_prefix(self.P2, skip_tokens=32)
        assert delta["start_tokens"] == 32
        assert delta["matched_tokens"] == 64
        assert delta["k"].shape[1] == 2          # pages 2..3 only
        # Tail-first: registered but unreachable (chain walks from 0).
        _stream_import(importer, delta)
        assert importer.prefix_peek(self.P2) == 0
        # Head arrives (full export; the tail pages dedupe at commit).
        free0 = importer.scheduler.allocator.num_free
        full = owner.export_prefix(self.P2)
        assert full["start_tokens"] == 0 and full["k"].shape[1] == 4
        _stream_import(importer, full)
        # 2 pages newly registered (head), 2 deduped back to the pool.
        assert importer.scheduler.allocator.num_free == free0 - 2
        assert importer.prefix_peek(self.P2) == 64
        got = importer.generate([self.P2], params)[0].output_token_ids
        assert got == ref

    def test_skip_past_match_is_a_miss(self, engines):
        owner, _ = engines
        with pytest.raises(KeyError, match="beyond"):
            owner.export_prefix(self.P2, skip_tokens=64)

    def test_export_reads_host_tier_in_place(self, engines):
        """A chain sitting in the HOST tier is served without restoring
        it into the device pool, without counters, byte-identical to the
        live-tier export — a peer's fetch must not perturb the owner."""
        _, importer = engines
        pc = importer.scheduler.prefix_cache
        ref_state = importer.export_prefix(self.P2)      # live-tier bytes
        pc.evict(len(pc))                # spills to importer's OWN host tier
        assert len(pc._host_entries) >= 4
        free0 = importer.scheduler.allocator.num_free
        host_hits0 = pc.host_hits
        state = importer.export_prefix(self.P2)
        np.testing.assert_array_equal(state["k"], ref_state["k"])
        np.testing.assert_array_equal(state["v"], ref_state["v"])
        assert importer.scheduler.allocator.num_free == free0
        assert pc.host_hits == host_hits0
        assert len(pc) == 0              # nothing restored to the live tier


class TestRemoteSpill:
    """The eviction ladder's remote rung: pages the local host tier could
    not take move to a PEER's host tier and second-chance back into its
    device pool byte-identically."""

    SPILL_PROMPT = np.random.default_rng(11).integers(1, 500, 80).tolist()

    def test_spill_to_peer_host_tier_and_second_chance(self, engines):
        owner, importer = engines
        params = SamplingParams(max_tokens=6, temperature=0.0)
        ref = owner.generate([self.SPILL_PROMPT], params)[0].output_token_ids
        spills = []
        assert owner.enable_fleet_spill(
            lambda d, k, v: (spills.append((d, k, v)) or True))
        pc = owner.scheduler.prefix_cache
        pc.evict(len(pc))
        # The owner has no host tier: EVERY evicted page took the remote
        # rung (this prompt's chain + whatever earlier tests cached).
        assert len(spills) >= 4
        owner.scheduler.prefix_cache.fleet_spill = None
        accepted = sum(importer.accept_remote_spill(d, k, v)
                       for d, k, v in spills)
        # Digests the importer already holds (earlier tests imported the
        # shared PROMPT chain) are refused; the SPILL chain is new.
        assert accepted >= 4
        assert importer.prefix_peek(self.SPILL_PROMPT) == 64
        host_hits0 = importer.scheduler.prefix_cache.host_hits
        got = importer.generate([self.SPILL_PROMPT],
                                params)[0].output_token_ids
        assert got == ref
        assert importer.scheduler.prefix_cache.host_hits >= host_hits0 + 4

    def test_duplicate_and_malformed_spills_refused(self, engines):
        owner, importer = engines
        k = np.zeros((2, 1, 16, 64), np.float32)
        # wrong geometry
        assert not importer.accept_remote_spill("aa", k[:, :, :8], k[:, :, :8])
        # bad digest spelling
        assert not importer.accept_remote_spill("not-hex", k, k)
        # owner has no host tier at all
        assert not owner.accept_remote_spill("ab" * 16, k, k)


class TestFleetHTTP:
    """ONE two-server scenario over real sockets: pull-on-hint is
    byte-identical and counted; the roofline gate skips; an out-of-pool
    hint and the kv_pull_fail chaos site both degrade to local recompute
    with the trigger in the trace ring and the flight recorder."""

    def test_pull_skip_allowlist_and_chaos(self):
        from aiohttp import web as aioweb

        import aiohttp
        from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
        from kubernetes_gpu_cluster_tpu.serving.errors import \
            PREFIX_SOURCE_HEADER
        from kubernetes_gpu_cluster_tpu.serving.fleet_cache import PullPolicy

        async def scenario():
            runners = []

            async def serve(**kw):
                srv = build_server(_engine_config(), None, "debug-tiny",
                                   **kw)
                runner = aioweb.AppRunner(srv.build_app())
                await runner.setup()
                site = aioweb.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                runners.append(runner)
                return srv, f"http://127.0.0.1:{runner.addresses[0][1]}"

            try:
                sa, ua = await serve(fleet_prefix_cache=True)
                sb, ub = await serve(fleet_prefix_cache=True, peer_pool=[ua])
                assert sa.fleet_on and sb.fleet_on
                pulls = sb.engine.engine.obs.fleet_pulls
                prompt = np.random.default_rng(7).integers(
                    1, 200, 80).tolist()
                body = {"prompt": prompt, "max_tokens": 6,
                        "temperature": 0.0}
                async with aiohttp.ClientSession() as sess:
                    async def comp(base, js, hint=None):
                        headers = ({PREFIX_SOURCE_HEADER: hint}
                                   if hint else {})
                        async with sess.post(f"{base}/v1/completions",
                                             json=js,
                                             headers=headers) as resp:
                            assert resp.status == 200, await resp.text()
                            return (await resp.json())[
                                "choices"][0]["text"]

                    ref = await comp(ua, body)              # warm the owner
                    got = await comp(ub, body, hint=ua)     # pull into B
                    assert got == ref
                    assert pulls["ok"] == 1
                    assert sb.engine.engine.scheduler.prefix_cache.hits >= 1
                    # Same prefix again: already local -> skipped, not
                    # re-pulled (anti-thrash).
                    await comp(ub, dict(body, prompt=prompt[:64] + [9, 9]),
                               hint=ua)
                    assert pulls["skipped"] == 1 and pulls["ok"] == 1
                    # Roofline gate: a policy that prices every pull above
                    # recompute skips BEFORE any socket I/O.
                    sb._pull_policy = PullPolicy(
                        link_bytes_per_s=1.0, flops_per_s=1e15,
                        kv_bytes_per_token=1e6, flops_per_token=1.0,
                        min_tokens=16)
                    p2 = np.random.default_rng(8).integers(
                        1, 200, 80).tolist()
                    await comp(ua, dict(body, prompt=p2))
                    await comp(ub, dict(body, prompt=p2), hint=ua)
                    assert pulls["skipped"] == 2 and pulls["ok"] == 1
                    sb._pull_policy = build_pull_policy(
                        sb.engine.engine.model_config, 16, 4, "cpu")
                    # Out-of-pool hint: never fetched, local recompute.
                    p3 = np.random.default_rng(9).integers(
                        1, 200, 80).tolist()
                    ref3 = await comp(ua, dict(body, prompt=p3))
                    got3 = await comp(ub, dict(body, prompt=p3),
                                      hint="http://169.254.0.1:1")
                    assert got3 == ref3 and pulls["recompute"] == 1
                    # Chaos: kv_pull_fail degrades to recompute with the
                    # trigger recorded in trace ring + flight recorder.
                    configure_faults("kv_pull_fail")
                    p4 = np.random.default_rng(10).integers(
                        1, 200, 80).tolist()
                    ref4 = await comp(ua, dict(body, prompt=p4))
                    got4 = await comp(ub, dict(body, prompt=p4), hint=ua)
                    configure_faults(None)
                    assert got4 == ref4 and pulls["recompute"] == 2
                    events = [e for e in
                              sb.engine.engine.obs.tracer.events()
                              if e.kind == "fleet_prefix"]
                    assert any(e.args.get("outcome") == "recompute"
                               and "kv_pull_fail" in e.args.get("error", "")
                               for e in events)
                    # The flight recorder mirrors the emit (args are
                    # flattened into the event record).
                    flight = sb.engine.engine.obs.flight.export()["events"]
                    assert any(e.get("kind") == "fleet_prefix"
                               and e.get("outcome") == "recompute"
                               for e in flight)
                    # /metrics renders every outcome, zeros included.
                    async with sess.get(f"{ub}/metrics") as resp:
                        text = await resp.text()
                    assert ('kgct_fleet_prefix_pulls_total'
                            '{outcome="ok"} 1') in text
                    assert ('kgct_fleet_prefix_pulls_total'
                            '{outcome="recompute"} 2') in text
                    assert ('kgct_fleet_prefix_pulls_total'
                            '{outcome="skipped"} 2') in text
                    assert ('kgct_fleet_prefix_spills_total'
                            '{outcome="ok"} 0') in text
            finally:
                for runner in reversed(runners):
                    await runner.cleanup()

        asyncio.run(scenario())


class TestFleetOffByteIdentical:
    def test_flag_off_ignores_hint_and_renders_zeros(self):
        """fleet off: the hint header is inert, the fetch endpoint 404s,
        and the metrics render zeros — the byte-identity-with-off half of
        the acceptance contract at the serving layer (engine behavior off
        the fleet path is untouched by construction: no code runs)."""
        from aiohttp import web as aioweb

        import aiohttp
        from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
        from kubernetes_gpu_cluster_tpu.serving.errors import \
            PREFIX_SOURCE_HEADER

        async def scenario():
            srv = build_server(_engine_config(), None, "debug-tiny")
            assert not srv.fleet_on
            runner = aioweb.AppRunner(srv.build_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            url = f"http://127.0.0.1:{runner.addresses[0][1]}"
            try:
                async with aiohttp.ClientSession() as sess:
                    prompt = list(range(1, 40))
                    async with sess.post(
                            f"{url}/v1/completions",
                            json={"prompt": prompt, "max_tokens": 2,
                                  "temperature": 0.0},
                            headers={PREFIX_SOURCE_HEADER:
                                     "http://169.254.0.1:1"}) as resp:
                        assert resp.status == 200
                        await resp.read()
                    async with sess.post(
                            f"{url}/internal/fetch_prefix",
                            json={"prompt_token_ids": prompt}) as resp:
                        assert resp.status == 404
                    async with sess.post(
                            f"{url}/internal/fleet_spill",
                            data=b"x") as resp:
                        assert resp.status == 404
                    async with sess.get(f"{url}/metrics") as resp:
                        text = await resp.text()
                    for oc in ("ok", "recompute", "skipped"):
                        assert (f'kgct_fleet_prefix_pulls_total'
                                f'{{outcome="{oc}"}} 0') in text
            finally:
                await runner.cleanup()

        asyncio.run(scenario())
