"""Ring attention (sequence parallelism) vs the single-device oracle.

Runs on the virtual 8-device CPU mesh (conftest). The oracle is
ops.attention.ragged_prefill_attention_xla — the same one the Pallas prefill
kernel is tested against — so all three attention paths agree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.ops.attention import (
    ragged_prefill_attention_xla)
from kubernetes_gpu_cluster_tpu.parallel import make_mesh
from kubernetes_gpu_cluster_tpu.parallel.sp import (
    build_ring_prefill, sequence_sharding)


def _mk(T, nh, n_kv, hd, seg_lens, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.float32)
    seg, pos = [], []
    for s, ln in enumerate(seg_lens):
        seg += [s] * ln
        pos += list(range(ln))
    pad = T - len(seg)
    assert pad >= 0
    seg += [-1] * pad
    pos += [0] * pad
    return q, k, v, jnp.asarray(seg, jnp.int32), jnp.asarray(pos, jnp.int32)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_oracle(sp):
    T, nh, n_kv, hd = 64, 4, 2, 32
    mesh = make_mesh(sp=sp)
    q, k, v, seg, pos = _mk(T, nh, n_kv, hd, seg_lens=[23, 17, 11])
    scale = hd ** -0.5
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, scale)
    fn = build_ring_prefill(mesh, n_kv, nh // n_kv, scale)
    out = fn(q, k, v, seg, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_single_long_sequence():
    """The long-context case sp exists for: one sequence filling the batch."""
    T, nh, n_kv, hd = 128, 2, 1, 16
    mesh = make_mesh(sp=8)
    q, k, v, seg, pos = _mk(T, nh, n_kv, hd, seg_lens=[128])
    scale = hd ** -0.5
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, scale)
    fn = build_ring_prefill(mesh, n_kv, nh // n_kv, scale)
    out = fn(q, k, v, seg, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_sharded_inputs():
    """Inputs pre-placed with the sp sharding (no implicit reshard) work and
    produce sharded output."""
    T, nh, n_kv, hd = 64, 4, 2, 32
    mesh = make_mesh(sp=4)
    q, k, v, seg, pos = _mk(T, nh, n_kv, hd, seg_lens=[40, 20])
    sh = sequence_sharding(mesh)
    qs = jax.device_put(q, sh)
    ks = jax.device_put(k, sh)
    vs = jax.device_put(v, sh)
    segs = jax.device_put(seg, sh)
    poss = jax.device_put(pos, sh)
    scale = hd ** -0.5
    fn = build_ring_prefill(mesh, n_kv, nh // n_kv, scale)
    out = fn(qs, ks, vs, segs, poss)
    assert not out.is_fully_replicated
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_composes_with_tp():
    """sp x tp mesh: ring over sp while heads could shard over tp (the
    mesh layout serving long-context TP replicas would use)."""
    T, nh, n_kv, hd = 32, 4, 2, 16
    mesh = make_mesh(sp=2, tp=2, dp=2)
    q, k, v, seg, pos = _mk(T, nh, n_kv, hd, seg_lens=[30])
    scale = hd ** -0.5
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, scale)
    fn = build_ring_prefill(mesh, n_kv, nh // n_kv, scale)
    out = fn(q, k, v, seg, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
