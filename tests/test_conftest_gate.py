"""The conftest env-capability gate must inform, not mask.

18 pre-existing env failures (this container's jax: no top-level
``jax.shard_map``, no Pallas interpret-mode state discharge, no CPU
multiprocess collectives) are gated as SKIPS with per-class reasons.
The gate's danger mode is silent over-reach: a new, real failure
swallowed into the skip bucket. These tests pin both directions:

- every gated entry carries an explicit per-failure-class reason naming
  the env gap and "pre-existing" provenance;
- the gate table is EXACT — a test not in it (same file, different name;
  same name, different class) gets NO marker, so a genuine regression
  still fails;
- a capable env (jax.shard_map present) gates nothing at all.
"""

from pathlib import Path
from types import SimpleNamespace

import conftest


def _gates():
    # Build with gating FORCED ON so the pins hold even once the env
    # upgrades past jax.shard_map.
    return conftest._build_env_gates(have_shard_map=False)


class _FakeItem(SimpleNamespace):
    """The four attributes _apply_env_gates reads, plus marker capture."""

    def __init__(self, fname, name, cls_name=None):
        super().__init__(
            path=Path(f"/tests/{fname}"),
            originalname=name,
            name=name,
            cls=(type(cls_name, (), {}) if cls_name else None))
        self.markers = []

    def add_marker(self, marker):
        self.markers.append(marker)


class TestGateTable:
    def test_capable_env_gates_nothing(self):
        assert conftest._build_env_gates(have_shard_map=True) == {}

    def test_every_entry_has_env_gap_provenance(self):
        gates = _gates()
        assert gates, "forced gating must produce the table"
        for (fname, name), why in gates.items():
            assert why.startswith("env gap:"), (fname, name)
            assert "pre-existing since the seed" in why, (fname, name)

    def test_reasons_are_per_failure_class(self):
        gates = _gates()
        reasons = set(gates.values())
        assert len(reasons) == 3, "one reason per env-gap class"
        assert "shard_map" in gates[
            ("test_parallel.py", "test_pp_engine_matches_single_device")]
        assert "multiprocess" in gates[
            ("test_distributed.py", "test_two_process_jax_distributed")]
        assert "interpret-mode" in gates[
            ("test_pallas.py", "test_stacked_pool_layer_index")]
        # the class-qualified disambiguation entry is interpret-class
        assert "interpret-mode" in gates[
            ("test_pallas.py", "TestPagedDecodeKernel.test_matches_xla")]

    def test_gate_count_matches_recorded_env_failures(self):
        # 16 function-name keys + 1 class-qualified key covering the 18
        # recorded pre-existing failures (parametrization expands some).
        assert len(_gates()) == 17


class TestGateApplication:
    def test_gated_item_gets_skip_with_reason(self):
        item = _FakeItem("test_parallel.py",
                         "test_pp_engine_matches_single_device")
        applied = conftest._apply_env_gates([item], _gates())
        assert len(applied) == 1 and len(item.markers) == 1
        marker = item.markers[0]
        assert marker.name == "skip"
        assert "env gap" in marker.kwargs["reason"]

    def test_non_gated_failure_still_fails(self):
        """The 18 skips must not mask NEW breakage: a test the table does
        not name — even in the same heavily-gated files — gets no marker
        and would fail loudly."""
        items = [
            _FakeItem("test_parallel.py", "test_new_regression"),
            _FakeItem("test_pallas.py", "test_some_new_kernel"),
            _FakeItem("test_engine.py", "test_pp_engine_matches_single_device"),
        ]
        applied = conftest._apply_env_gates(items, _gates())
        assert applied == []
        assert all(item.markers == [] for item in items)

    def test_class_qualified_key_does_not_leak_to_other_classes(self):
        """test_matches_xla exists in several kernel-test classes; only
        TestPagedDecodeKernel's is env-gated. The others must run."""
        gated = _FakeItem("test_pallas.py", "test_matches_xla",
                          cls_name="TestPagedDecodeKernel")
        free = _FakeItem("test_pallas.py", "test_matches_xla",
                         cls_name="TestFlashPrefillKernel")
        conftest._apply_env_gates([gated, free], _gates())
        assert len(gated.markers) == 1
        assert free.markers == []

    def test_parametrized_names_match_on_originalname(self):
        item = _FakeItem("test_parallel.py",
                         "test_pp_decode_matches_single_device")
        item.name = "test_pp_decode_matches_single_device[4-2]"
        applied = conftest._apply_env_gates([item], _gates())
        assert len(applied) == 1

    def test_live_table_consistent_with_env(self):
        import jax
        if hasattr(jax, "shard_map"):
            assert conftest._ENV_GATED == {}
        else:
            assert conftest._ENV_GATED == _gates()
