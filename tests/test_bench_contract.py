"""Bench stdout contract + prefill roofline model.

The r5 official record landed ``"parsed": null`` because the driver-side
parser failed silently on the captured transcript. The contract is now
symmetric and documented: ``emit_result`` guarantees the last stdout line
is the JSON result, ``bench.py --help`` documents that guarantee, and
``parse_result_line`` is the reference consumer — these tests pin that a
driver-captured multi-line transcript (noise before AND after flushes,
blank lines, progress spam) round-trips, and that failures RAISE instead
of yielding null.
"""

import json
import math

import pytest

import bench


def _fake_results():
    return [{
        "model": "debug-tiny", "quantization": None, "batch": 8,
        "decode_window": 4, "prefill_budget": 256,
        "decode_tokens_per_sec": 123.4,
        "sampled_over_greedy": 0.95,
        "mixed_batch": True,
        "ttft_decomposition": {"queue_ms": 1.0, "prefill_ms": 2.0,
                               "first_fetch_ms": 3.0, "samples": 8},
    }]


class TestTranscriptParsing:
    def test_noisy_multiline_transcript_round_trips(self):
        """A realistic driver capture: library spam, blank lines, progress
        dots before the result line, trailing newlines after it."""
        result = bench.assemble_output(_fake_results(), "cpu")
        transcript = (
            "INFO something initialized\n"
            "downloading... 47%\n"
            "\n"
            "{'not': 'the result — a repr, not JSON'}\n"
            "warmup window 3/3 done\n"
            + json.dumps(result) + "\n\n"
        )
        parsed = bench.parse_result_line(transcript)
        assert parsed["value"] == 123.4
        assert parsed["unit"] == "tokens/s/chip"
        assert parsed["mixed_batch"] is True

    def test_emit_result_then_parse_round_trips(self, capsys):
        """emit_result -> parse_result_line is the full contract loop,
        including earlier unflushed stdout noise."""
        print("earlier unflushed noise")
        print("more noise { with: braces }")
        bench.emit_result(bench.assemble_output(_fake_results(), "cpu"))
        captured = capsys.readouterr().out
        parsed = bench.parse_result_line(captured)
        assert parsed["backend"] == "cpu"
        assert not math.isnan(parsed["vs_baseline"])

    def test_garbage_last_line_raises_not_null(self):
        with pytest.raises(ValueError, match="not the bench result JSON"):
            bench.parse_result_line("noise\n" + json.dumps({"ok": 1})
                                    + "\ntrailing non-json garbage\n")

    def test_empty_transcript_raises(self):
        with pytest.raises(ValueError, match="empty bench stdout"):
            bench.parse_result_line("\n\n   \n")

    def test_non_object_result_raises(self):
        with pytest.raises(ValueError, match="expected a JSON object"):
            bench.parse_result_line("[1, 2, 3]\n")


class TestHelpDocumentsContract:
    def test_help_text_states_last_line_contract(self):
        text = bench.build_arg_parser().format_help()
        assert "LAST non-empty line of stdout" in text
        assert "single-line JSON object" in text
        assert "parse_result_line" in text

    def test_help_lists_env_knobs(self):
        text = bench.build_arg_parser().format_help()
        for knob in ("KGCT_BENCH_MODEL", "KGCT_BENCH_MIXED",
                     "KGCT_BENCH_PREFILL_BUDGET"):
            assert knob in text


class TestPrefillRoofline:
    def _mcfg(self):
        from kubernetes_gpu_cluster_tpu.config import get_model_config
        return get_model_config("tinyllama-1.1b")

    def test_fields_and_sanity(self):
        pf = bench._roofline_prefill(self._mcfg(), None, 2048)
        for k in ("tokens_modeled", "flops_per_step", "flops_per_token",
                  "bytes_per_step", "flops_per_byte", "compute_bound_ms",
                  "hbm_bound_ms"):
            assert k in pf, k
        assert pf["tokens_modeled"] == 2048
        assert pf["flops_per_step"] > 0 and pf["bytes_per_step"] > 0
        assert pf["flops_per_byte"] > 0
        # budget-sized prefill is compute-bound: its arithmetic intensity
        # beats the chip's FLOPs/byte balance point, so the compute bound is
        # the binding one — the TTFT arithmetic target
        balance = (bench.CHIP_TFLOPS_BF16 * 1e12) / (bench.CHIP_HBM_GBPS * 1e9)
        assert pf["flops_per_byte"] > balance
        assert pf["compute_bound_ms"] > pf["hbm_bound_ms"]

    def test_intensity_grows_with_tokens(self):
        """More tokens amortize the same weight stream: FLOPs/byte must be
        monotone in T (the reason mixed batching rides prefill steps)."""
        mcfg = self._mcfg()
        small = bench._roofline_prefill(mcfg, None, 128)
        big = bench._roofline_prefill(mcfg, None, 4096)
        assert big["flops_per_byte"] > small["flops_per_byte"]

    def test_int8_halves_weight_stream(self):
        mcfg = self._mcfg()
        bf16 = bench._roofline_prefill(mcfg, None, 512)
        q8 = bench._roofline_prefill(mcfg, "int8", 512)
        assert q8["bytes_per_step"] < bf16["bytes_per_step"]
        assert q8["flops_per_step"] == bf16["flops_per_step"]

    def test_json_serializable(self):
        pf = bench._roofline_prefill(self._mcfg(), "int8", 1024)
        assert json.loads(json.dumps(pf)) == pf
