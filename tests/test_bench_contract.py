"""Bench stdout contract + prefill roofline model.

The r5 official record landed ``"parsed": null`` because the driver-side
parser failed silently on the captured transcript. The contract is now
symmetric and documented: ``emit_result`` guarantees the last stdout line
is the JSON result, ``bench.py --help`` documents that guarantee, and
``parse_result_line`` is the reference consumer — these tests pin that a
driver-captured multi-line transcript (noise before AND after flushes,
blank lines, progress spam) round-trips, and that failures RAISE instead
of yielding null.
"""

import json
import math
from pathlib import Path

import pytest

import bench

REPO = Path(__file__).resolve().parent.parent


def _fake_results():
    return [{
        "model": "debug-tiny", "quantization": None, "batch": 8,
        "decode_window": 4, "prefill_budget": 256,
        "decode_tokens_per_sec": 123.4,
        "sampled_over_greedy": 0.95,
        "mixed_batch": True,
        "ttft_decomposition": {"queue_ms": 1.0, "prefill_ms": 2.0,
                               "first_fetch_ms": 3.0, "samples": 8},
    }]


class TestTranscriptParsing:
    def test_noisy_multiline_transcript_round_trips(self):
        """A realistic driver capture: library spam, blank lines, progress
        dots before the result line, trailing newlines after it."""
        result = bench.assemble_output(_fake_results(), "cpu")
        transcript = (
            "INFO something initialized\n"
            "downloading... 47%\n"
            "\n"
            "{'not': 'the result — a repr, not JSON'}\n"
            "warmup window 3/3 done\n"
            + json.dumps(result) + "\n\n"
        )
        parsed = bench.parse_result_line(transcript)
        assert parsed["value"] == 123.4
        assert parsed["unit"] == "tokens/s/chip"
        assert parsed["mixed_batch"] is True

    def test_emit_result_then_parse_round_trips(self, capsys):
        """emit_result -> parse_result_line is the full contract loop,
        including earlier unflushed stdout noise."""
        print("earlier unflushed noise")
        print("more noise { with: braces }")
        bench.emit_result(bench.assemble_output(_fake_results(), "cpu"))
        captured = capsys.readouterr().out
        parsed = bench.parse_result_line(captured)
        assert parsed["backend"] == "cpu"
        assert not math.isnan(parsed["vs_baseline"])

    def test_garbage_last_line_raises_not_null(self):
        with pytest.raises(ValueError, match="not the bench result JSON"):
            bench.parse_result_line("noise\n" + json.dumps({"ok": 1})
                                    + "\ntrailing non-json garbage\n")

    def test_empty_transcript_raises(self):
        with pytest.raises(ValueError, match="empty bench stdout"):
            bench.parse_result_line("\n\n   \n")

    def test_non_object_result_raises(self):
        with pytest.raises(ValueError, match="expected a JSON object"):
            bench.parse_result_line("[1, 2, 3]\n")


class TestDriverRecordGuard:
    """The official-record failure modes, pinned against REAL driver
    captures: BENCH_r04.json parsed fine (779-char tail, noisy WARNING/
    INFO preamble); BENCH_r05.json landed "parsed": null because its
    result line outgrew the driver's 2000-char tail window and the capture
    DECAPITATED it. emit_result now bounds the line (RESULT_LINE_MAX) so a
    tail capture can never cut the head off again."""

    def _real_record(self, name):
        rec = json.loads((REPO / name).read_text())
        assert {"tail", "parsed"} <= set(rec)
        return rec

    def test_real_r04_noisy_transcript_round_trips(self):
        """A genuine driver capture — jax platform warnings, engine INFO
        lines, then the result — must parse to exactly what the driver
        recorded."""
        rec = self._real_record("BENCH_r04.json")
        parsed = bench.parse_result_line(rec["tail"])
        assert parsed == rec["parsed"]
        assert parsed["unit"] == "tokens/s/chip"

    def test_real_r05_decapitated_tail_raises_not_null(self):
        """The r5 failure mode itself: the tail window cut the head off an
        oversized result line. parse_result_line must RAISE (the driver
        records the error) — a silent null is how r5's numbers vanished."""
        rec = self._real_record("BENCH_r05.json")
        assert rec["parsed"] is None   # the incident this guard pins
        with pytest.raises(ValueError, match="not the bench result JSON"):
            bench.parse_result_line(rec["tail"])

    def _oversized_result(self):
        # r05-scale: many configs, each carrying the nested bench blocks
        configs = [dict(_fake_results()[0],
                        roofline={"hbm_gbps": 575.7, "mfu": 0.29,
                                  "chip": {"hbm_gbps_peak": 819.0}},
                        sustained_load={"ttft_p50_ms": 3436.8,
                                        "ttft_p95_ms": 6331.1},
                        speculative={"spec": {"acceptance_ratio": 0.8}},
                        trial=i)
                   for i in range(8)]
        return bench.assemble_output(configs, "tpu")

    def test_oversized_result_survives_a_2000_char_tail(self, capsys):
        out = self._oversized_result()
        assert len(json.dumps(out)) > 2000   # genuinely r05-sized
        print("warmup noise " * 40)
        bench.emit_result(out)
        captured = capsys.readouterr()
        tail = captured.out[-2000:]          # the driver's capture window
        parsed = bench.parse_result_line(tail)
        assert parsed["value"] == out["value"]
        assert parsed["metric"] == out["metric"]
        assert parsed["configs_on_stderr"] is True
        # nothing lost: the full result rides stderr
        full_lines = [ln for ln in captured.err.splitlines()
                      if ln.startswith("FULL_RESULT: ")]
        assert len(full_lines) == 1
        assert json.loads(full_lines[0][len("FULL_RESULT: "):]) == out

    def test_result_line_always_bounded(self, capsys):
        bench.emit_result(self._oversized_result())
        last = capsys.readouterr().out.splitlines()[-1]
        assert len(last) <= bench.RESULT_LINE_MAX < 2000

    def test_headline_bloat_degrades_but_never_fails(self, capsys):
        """Even when a headline block itself outgrows the bound (so
        dropping configs isn't enough), emit_result degrades block by
        block — the primary metric/value/unit always land on stdout,
        bounded. It must never raise or emit an unbounded line."""
        out = self._oversized_result()
        out["ttft_decomposition"] = {f"k{i}": float(i) for i in range(400)}
        bench.emit_result(out)
        last = capsys.readouterr().out.splitlines()[-1]
        assert len(last) <= bench.RESULT_LINE_MAX
        parsed = json.loads(last)
        assert parsed["metric"] == out["metric"]
        assert parsed["value"] == out["value"]
        assert "ttft_decomposition" not in parsed

    def test_small_result_passes_through_unshrunk(self, capsys):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert bench.compact_result(out) is out
        bench.emit_result(out)
        parsed = bench.parse_result_line(capsys.readouterr().out)
        assert parsed == json.loads(json.dumps(out))
        assert "configs" in parsed

    def test_help_documents_the_bound(self):
        text = bench.build_arg_parser().format_help()
        assert "RESULT_LINE_MAX" in text and "tail" in text.lower()


class TestHelpDocumentsContract:
    def test_help_text_states_last_line_contract(self):
        text = bench.build_arg_parser().format_help()
        assert "LAST non-empty line of stdout" in text
        assert "single-line JSON object" in text
        assert "parse_result_line" in text

    def test_help_lists_env_knobs(self):
        text = bench.build_arg_parser().format_help()
        for knob in ("KGCT_BENCH_MODEL", "KGCT_BENCH_MIXED",
                     "KGCT_BENCH_PREFILL_BUDGET"):
            assert knob in text


class TestPrefillRoofline:
    def _mcfg(self):
        from kubernetes_gpu_cluster_tpu.config import get_model_config
        return get_model_config("tinyllama-1.1b")

    def test_fields_and_sanity(self):
        pf = bench._roofline_prefill(self._mcfg(), None, 2048)
        for k in ("tokens_modeled", "flops_per_step", "flops_per_token",
                  "bytes_per_step", "flops_per_byte", "compute_bound_ms",
                  "hbm_bound_ms"):
            assert k in pf, k
        assert pf["tokens_modeled"] == 2048
        assert pf["flops_per_step"] > 0 and pf["bytes_per_step"] > 0
        assert pf["flops_per_byte"] > 0
        # budget-sized prefill is compute-bound: its arithmetic intensity
        # beats the chip's FLOPs/byte balance point, so the compute bound is
        # the binding one — the TTFT arithmetic target
        balance = (bench.CHIP_TFLOPS_BF16 * 1e12) / (bench.CHIP_HBM_GBPS * 1e9)
        assert pf["flops_per_byte"] > balance
        assert pf["compute_bound_ms"] > pf["hbm_bound_ms"]

    def test_intensity_grows_with_tokens(self):
        """More tokens amortize the same weight stream: FLOPs/byte must be
        monotone in T (the reason mixed batching rides prefill steps)."""
        mcfg = self._mcfg()
        small = bench._roofline_prefill(mcfg, None, 128)
        big = bench._roofline_prefill(mcfg, None, 4096)
        assert big["flops_per_byte"] > small["flops_per_byte"]

    def test_int8_halves_weight_stream(self):
        mcfg = self._mcfg()
        bf16 = bench._roofline_prefill(mcfg, None, 512)
        q8 = bench._roofline_prefill(mcfg, "int8", 512)
        assert q8["bytes_per_step"] < bf16["bytes_per_step"]
        assert q8["flops_per_step"] == bf16["flops_per_step"]

    def test_int4_packs_below_int8(self):
        """The int4 rung streams packed bytes + group scales: under int8's
        stream but above an idealized scale-free half (the scales are real
        bytes; pretending otherwise would flatter the roofline)."""
        mcfg = self._mcfg()
        q8 = bench._roofline_prefill(mcfg, "int8", 512)
        q4 = bench._roofline_prefill(mcfg, "int4", 512)
        assert q4["bytes_per_step"] < q8["bytes_per_step"]
        assert q4["flops_per_step"] == q8["flops_per_step"]
        w8 = bench._weight_stream_bytes(mcfg, "int8")
        w4 = bench._weight_stream_bytes(mcfg, "int4")
        assert w8 // 2 < w4 <= 0.55 * w8

    def test_json_serializable(self):
        pf = bench._roofline_prefill(self._mcfg(), "int8", 1024)
        assert json.loads(json.dumps(pf)) == pf


class TestPrefixReuseContract:
    """The prefix_reuse phase must ride the bounded last-line contract: its
    headline field survives parse_result_line and the full block lives in
    the primary config (falling to stderr with the rest of "configs" when
    the line must shrink)."""

    def test_headline_parses_in_last_line(self):
        results = _fake_results()
        results[-1]["prefix_reuse"] = {
            "n_requests": 6, "shared_prefix_tokens": 128, "tail_tokens": 16,
            "ttft_cold_p50_ms": 11.2, "ttft_warm_p50_ms": 5.6,
            "warm_over_cold": 0.5, "cache_hits": 6, "cache_misses": 7,
        }
        out = bench.assemble_output(results, "cpu")
        parsed = bench.parse_result_line(json.dumps(out) + "\n")
        assert parsed["prefix_warm_over_cold_ttft"] == 0.5
        assert parsed["configs"][-1]["prefix_reuse"]["cache_hits"] == 6

    def test_headline_is_droppable_under_the_bound(self):
        assert "prefix_warm_over_cold_ttft" in bench._DROPPABLE_HEADLINE
        out = bench.assemble_output(_fake_results(), "cpu")
        line = json.dumps(bench.compact_result(out))
        assert len(line) <= bench.RESULT_LINE_MAX

    def test_absent_phase_yields_null_headline(self):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert out["prefix_warm_over_cold_ttft"] is None


class TestRouterPhaseContract:
    """KGCT_BENCH_ROUTER rides the bounded last-line contract like the
    other phases: headline parseable from the last stdout line, droppable
    under the byte bound, null when the phase was skipped."""

    def test_headline_parses_in_last_line(self):
        results = _fake_results()
        results[-1]["router_affinity"] = {
            "replicas": 2, "sessions": 3, "rounds": 3,
            "least_inflight": {"ttft_warm_p50_ms": 15.2,
                               "per_replica": [{"hit_ratio": 0.4}]},
            "prefix_affinity": {"ttft_warm_p50_ms": 11.3,
                                "affinity_hit_ratio": 1.0,
                                "per_replica": [{"hit_ratio": 0.667}]},
            "warm_ttft_ratio": 0.743,
        }
        out = bench.assemble_output(results, "cpu")
        parsed = bench.parse_result_line(json.dumps(out) + "\n")
        assert parsed["router_affinity_warm_over_li_ttft"] == 0.743
        assert (parsed["configs"][-1]["router_affinity"]["prefix_affinity"]
                ["affinity_hit_ratio"]) == 1.0

    def test_headline_is_droppable_under_the_bound(self):
        assert ("router_affinity_warm_over_li_ttft"
                in bench._DROPPABLE_HEADLINE)
        out = bench.assemble_output(_fake_results(), "cpu")
        line = json.dumps(bench.compact_result(out))
        assert len(line) <= bench.RESULT_LINE_MAX

    def test_absent_phase_yields_null_headline(self):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert out["router_affinity_warm_over_li_ttft"] is None


class TestDrainPhaseContract:
    """KGCT_BENCH_DRAIN rides the bounded last-line contract like the
    other phases: headline parseable from the last stdout line, droppable
    under the byte bound, null when the phase was skipped."""

    def test_headline_parses_in_last_line(self):
        results = _fake_results()
        results[-1]["drain"] = {
            "sessions": 6, "max_new": 48,
            "wait": {"drain_seconds": 4.1, "complete_streams": 6,
                     "migrations_push_fallback": 3},
            "migrate": {"drain_seconds": 1.4, "complete_streams": 6,
                        "migrations_push_ok": 3,
                        "failovers": {"import": 3}},
            "drain_migrate_over_wait_seconds": 0.341,
        }
        out = bench.assemble_output(results, "cpu")
        parsed = bench.parse_result_line(json.dumps(out) + "\n")
        assert parsed["drain_migrate_over_wait_seconds"] == 0.341
        assert parsed["configs"][-1]["drain"]["migrate"][
            "migrations_push_ok"] == 3

    def test_headline_is_droppable_under_the_bound(self):
        assert ("drain_migrate_over_wait_seconds"
                in bench._DROPPABLE_HEADLINE)
        out = bench.assemble_output(_fake_results(), "cpu")
        line = json.dumps(bench.compact_result(out))
        assert len(line) <= bench.RESULT_LINE_MAX

    def test_absent_phase_yields_null_headline(self):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert out["drain_migrate_over_wait_seconds"] is None

    def test_help_lists_drain_knobs(self):
        text = bench.build_arg_parser().format_help()
        for knob in ("KGCT_BENCH_DRAIN", "KGCT_BENCH_DRAIN_SESSIONS",
                     "KGCT_BENCH_DRAIN_MAX_NEW"):
            assert knob in text


class TestFleetCachePhaseContract:
    """KGCT_BENCH_FLEET_CACHE rides the bounded last-line contract like
    the other phases: headline parseable from the last stdout line,
    droppable under the byte bound, null when the phase was skipped."""

    def test_headline_parses_in_last_line(self):
        results = _fake_results()
        results[-1]["fleet_cache"] = {
            "sessions": 3, "shared_prefix_tokens": 384, "tail_tokens": 16,
            "recompute": {"warm_ttft_p50_ms": 30.5, "pulls_ok": 0},
            "pull": {"warm_ttft_p50_ms": 17.2, "pulls_ok": 4,
                     "pulled_bytes": 1580314},
            "fleet_prefix_pull_over_recompute_ttft": 0.564,
        }
        out = bench.assemble_output(results, "cpu")
        parsed = bench.parse_result_line(json.dumps(out) + "\n")
        assert parsed["fleet_prefix_pull_over_recompute_ttft"] == 0.564
        assert parsed["configs"][-1]["fleet_cache"]["pull"]["pulls_ok"] == 4

    def test_headline_is_droppable_under_the_bound(self):
        assert ("fleet_prefix_pull_over_recompute_ttft"
                in bench._DROPPABLE_HEADLINE)
        out = bench.assemble_output(_fake_results(), "cpu")
        line = json.dumps(bench.compact_result(out))
        assert len(line) <= bench.RESULT_LINE_MAX

    def test_absent_phase_yields_null_headline(self):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert out["fleet_prefix_pull_over_recompute_ttft"] is None

    def test_help_lists_fleet_knobs(self):
        text = bench.build_arg_parser().format_help()
        for knob in ("KGCT_BENCH_FLEET_CACHE", "KGCT_BENCH_FLEET_SESSIONS",
                     "KGCT_BENCH_FLEET_SHARED", "KGCT_FLEET_BW_GBPS",
                     "KGCT_FLEET_FLOPS"):
            assert knob in text


class TestIntegrityHeadlineContract:
    """kv_integrity_overhead_ratio (the fleet-cache phase's third arm)
    rides the same bounded last-line contract: droppable, null when the
    phase was skipped."""

    def test_headline_parses_and_is_droppable(self):
        results = _fake_results()
        results[-1]["fleet_cache"] = {
            "pull": {"warm_ttft_p50_ms": 17.2},
            "pull_integrity_off": {"warm_ttft_p50_ms": 16.9},
            "kv_integrity_overhead_ratio": 1.018,
        }
        out = bench.assemble_output(results, "cpu")
        parsed = bench.parse_result_line(json.dumps(out) + "\n")
        assert parsed["kv_integrity_overhead_ratio"] == 1.018
        assert "kv_integrity_overhead_ratio" in bench._DROPPABLE_HEADLINE

    def test_absent_phase_yields_null_headline(self):
        out = bench.assemble_output(_fake_results(), "cpu")
        assert out["kv_integrity_overhead_ratio"] is None
