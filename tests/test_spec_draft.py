"""Draft-model speculative decoding + acceptance-adaptive k + spec×mixed.

The bars, mirroring tests/test_spec_decode.py's for the n-gram rung:

1. LOSSLESSNESS with a real draft MODEL: greedy output is byte-identical
   to non-spec whether the draft is an oracle (same params — everything
   accepts) or a mismatched model (nearly everything rejects); seeded
   sampling reproduces. The ops-level chi-square distribution pin is
   unchanged (the verify sampler never changed — drafts are one-hot q
   either way).
2. DRAFT-POOL SYNC: the runner's valid/tail bookkeeping keeps the draft
   KV consistent across accept/reject/bonus commits with ONE catch-up
   feed per round in steady state; legacy-decode gaps trigger the reset
   prefill; retained state dies with the request and frees its pages.
3. ADAPTIVE K: a garbage draft decays k down the ladder to 0 (spec off,
   plain decode byte-identical), and the idle cooldown re-probes so a
   recovered workload climbs back.
4. SPEC×MIXED: chunk + verify slices ride one dispatched step
   (step kind "spec_mixed"), byte-identical to the mixed-only engine for
   greedy AND seeded sampling, abort-mid-chunk releases pages, and the
   CLI/metrics surfaces are wired (argparse hygiene, kgct_spec_current_k,
   draft-phase counters, trace attribution).

Tier-1 budget: one module params pytree, short generations, tiny configs;
the heavier compile-bound pins live in tests/test_compile_guard.py.
"""

import numpy as np
import pytest

import jax

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import (LLMEngine, SamplingParams,
                                               Sequence)
from kubernetes_gpu_cluster_tpu.engine.spec import AdaptiveK, DraftProposer
from kubernetes_gpu_cluster_tpu.engine.spec.draft_model import (
    DraftModelRunner, build_draft_runner)
from kubernetes_gpu_cluster_tpu.models import llama as model_lib

_MODEL = get_model_config("debug-tiny")
_PARAMS = model_lib.init_params(_MODEL, jax.random.key(7))

REPETITIVE = [7, 3, 9, 11] * 8
PLAIN = [5, 99, 23, 44, 17, 301, 12]


def _cfg(spec: bool, draft=None, adaptive=False, k: int = 4,
         mixed: bool = False, max_prefill: int = 256, k_max=None):
    return EngineConfig(
        model=_MODEL,
        cache=CacheConfig(page_size=8, num_pages=192),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=max_prefill,
            decode_buckets=(1, 2, 4), prefill_buckets=(32, 64, 128, 256),
            decode_window=8, mixed_batch_enabled=mixed,
            spec_decode_enabled=spec, num_speculative_tokens=k,
            spec_draft_model=draft, spec_adaptive_k=adaptive,
            spec_k_max=k_max))


def make_engine(spec: bool, **kw):
    draft_params = kw.pop("draft_params", None)
    return LLMEngine(_cfg(spec, **kw), params=_PARAMS,
                     draft_params=draft_params)


class _GarbageProposer(DraftProposer):
    def __init__(self, k, token=1):
        super().__init__(k)
        self.token = token

    def propose(self, token_ids):
        return [self.token] * self.k


class TestDraftModelByteIdentity:
    def test_oracle_draft_greedy_identical_and_accepts(self):
        """Draft == target params: every greedy draft IS the argmax, so
        acceptance is ~1.0 and output must still be byte-identical to
        non-spec (the accept rule emits the argmax either way)."""
        sp = SamplingParams(max_tokens=24, temperature=0.0)
        prompts = [list(REPETITIVE), list(PLAIN)]
        ref = [o.output_token_ids
               for o in make_engine(False).generate(prompts, sp)]
        eng = make_engine(True, draft="debug-tiny", draft_params=_PARAMS)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        assert eng.obs.step_kind_counts["spec"] > 0
        assert eng.obs.spec_acceptance_ratio() > 0.9
        assert eng.obs.spec_draft_tokens > 0
        # both pools drained
        alloc = eng.scheduler.allocator
        assert alloc.num_free == alloc.num_pages - 1

    def test_mismatched_draft_greedy_identical(self):
        """A draft model with DIFFERENT weights drafts mostly-rejected
        garbage; the rolled-back state must keep the output byte-identical
        (losslessness does not depend on draft quality)."""
        sp = SamplingParams(max_tokens=16, temperature=0.0)
        prompts = [list(REPETITIVE), list(PLAIN)]
        ref = [o.output_token_ids
               for o in make_engine(False).generate(prompts, sp)]
        eng = make_engine(True, draft="debug-tiny")
        eng.scheduler.spec_proposer = build_draft_runner(
            eng.config, "debug-tiny", seed=123)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        assert eng.obs.step_kind_counts["spec"] > 0
        ratio = eng.obs.spec_acceptance_ratio()
        assert ratio is not None and ratio < 0.5

    def test_seeded_sampled_reproducible_with_draft_model(self):
        sp = SamplingParams(max_tokens=12, temperature=0.9, seed=5)
        a = make_engine(True, draft="debug-tiny",
                        draft_params=_PARAMS).generate([list(REPETITIVE)],
                                                       sp)[0]
        b = make_engine(True, draft="debug-tiny",
                        draft_params=_PARAMS).generate([list(REPETITIVE)],
                                                       sp)[0]
        assert a.output_token_ids == b.output_token_ids


class TestDraftRunnerSync:
    """Unit pins on the runner's valid/tail bookkeeping — no engine, real
    Sequence objects driving propose_batch directly."""

    def _runner(self, k=4):
        return DraftModelRunner(_cfg(True, draft="debug-tiny", k=k),
                                _MODEL, params=_PARAMS)

    def test_first_round_resets_then_steady_state_is_one_feed(self):
        r = self._runner()
        seq = Sequence("r", list(REPETITIVE), SamplingParams())
        d1 = r.propose_batch([seq], 4)[0]
        assert len(d1) == 4
        resets_after_first = r.num_reset_prefills
        assert resets_after_first >= 1          # prompt ingestion
        # verifier accepts 2 drafts + resamples a different 3rd token
        seq.append_token(d1[0])
        seq.append_token(d1[1])
        seq.append_token((d1[2] + 1) % _MODEL.vocab_size)
        d2 = r.propose_batch([seq], 4)[0]
        assert len(d2) == 4
        # steady state: gap absorbed by the round's own dispatches
        assert r.num_reset_prefills == resets_after_first

    def test_all_accepted_plus_bonus_keeps_sync(self):
        r = self._runner()
        seq = Sequence("r", list(REPETITIVE), SamplingParams())
        d1 = r.propose_batch([seq], 4)[0]
        for t in d1:                       # all k accepted
            seq.append_token(t)
        seq.append_token((d1[-1] + 3) % _MODEL.vocab_size)   # bonus
        resets = r.num_reset_prefills
        d2 = r.propose_batch([seq], 4)[0]
        # gap is 2 (d_k's KV was never fed + the bonus): absorbed in-round,
        # costing one draft slot, no reset
        assert len(d2) == 3
        assert r.num_reset_prefills == resets

    def test_legacy_window_gap_triggers_reset(self):
        r = self._runner(k=3)
        seq = Sequence("r", list(REPETITIVE), SamplingParams())
        r.propose_batch([seq], 3)
        resets = r.num_reset_prefills
        for t in range(8):                 # a legacy decode window's commits
            seq.append_token((t * 13 + 5) % _MODEL.vocab_size)
        d = r.propose_batch([seq], 3)[0]
        assert len(d) == 3
        assert r.num_reset_prefills > resets

    def test_retain_frees_dropped_rows_pages(self):
        r = self._runner()
        seqs = [Sequence(f"r{i}", list(REPETITIVE), SamplingParams())
                for i in range(3)]
        r.propose_batch(seqs, 4)
        free_mid = r.allocator.num_free
        assert free_mid < r.allocator.num_pages - 1
        r.retain(["r0"])                   # r1/r2 finished
        assert r.allocator.num_free > free_mid
        r.retain([])
        assert r.allocator.num_free == r.allocator.num_pages - 1

    def test_vocab_mismatch_rejected(self):
        with pytest.raises(ValueError, match="vocab"):
            DraftModelRunner(_cfg(True, draft="opt-125m"),
                             get_model_config("opt-125m"))


class TestAdaptiveK:
    def test_ladder_and_moves(self):
        c = AdaptiveK(k_max=6, window=2)
        assert c.ladder == (0, 1, 2, 4, 6)
        assert c.current_k == 6
        c.observe(12, 0)
        c.observe(12, 0)                   # window full, ratio 0 -> down
        assert c.current_k == 4
        for _ in range(3 * 2):
            c.observe(12, 0)
        assert c.current_k == 0            # decayed to the floor
        for _ in range(c.cooldown):
            c.tick_idle()
        assert c.current_k == 1            # re-probe at the smallest rung
        c.observe(10, 10)
        c.observe(10, 10)                  # ratio 1 -> climb
        assert c.current_k == 2

    def test_engine_garbage_draft_decays_to_zero_and_recovers(self):
        """End-to-end throttle: a garbage proposer drags k to 0 (steps
        revert to plain decode — byte-identical output), and the idle
        cooldown re-probes so a good proposer climbs back."""
        sp = SamplingParams(max_tokens=72, temperature=0.0)
        eng = make_engine(True, adaptive=True, k=4)
        eng.scheduler.spec_proposer = _GarbageProposer(4, token=1)
        ctrl = eng.scheduler.spec_controller
        ctrl.window = 3
        ctrl.cooldown = 6
        ref = make_engine(False).generate([list(REPETITIVE)], sp)[0]
        out = eng.generate([list(REPETITIVE)], sp)[0]
        assert out.output_token_ids == ref.output_token_ids
        assert ctrl.num_steps_down >= 3          # rode the ladder down
        assert eng.obs.step_kind_counts["decode"] > 0   # k=0 stretches
        # gauge mirrors the live rung
        assert eng.obs.spec_current_k == ctrl.current_k
        # recovery: cooldown ticks at k=0 re-probe, and a now-useful
        # proposer climbs
        ctrl.current_k = 0
        ctrl._idle_ticks = 0
        eng.scheduler.spec_proposer = build_draft_runner(
            eng.config, "debug-tiny", params=_PARAMS)
        out2 = eng.generate([list(REPETITIVE)], sp)[0]
        assert out2.output_token_ids == ref.output_token_ids
        assert ctrl.current_k >= 1
        assert ctrl.num_steps_up >= 1


class TestSpecMixedInterop:
    def _staggered(self, eng):
        """One session decodes (draftable history), then a long chunking
        prompt + a short one arrive — chunk and verify slices must share
        steps."""
        sp = SamplingParams(max_tokens=20, temperature=0.0)
        outs = {}
        eng.add_request("a", list(REPETITIVE), sp)
        for _ in range(10):
            for o in eng.step():
                if o.finished:
                    outs[o.request_id] = o.output_token_ids
        eng.add_request("b", REPETITIVE * 3, sp)
        eng.add_request("c", list(REPETITIVE), sp)
        while eng.has_unfinished_requests():
            for o in eng.step():
                if o.finished:
                    outs[o.request_id] = o.output_token_ids
        return outs

    def test_chunk_plus_verify_slices_in_one_step(self):
        ref = self._staggered(make_engine(False, mixed=True,
                                          max_prefill=32))
        eng = make_engine(True, mixed=True, max_prefill=32,
                          draft="debug-tiny", draft_params=_PARAMS)
        got = self._staggered(eng)
        assert got == ref
        assert eng.obs.step_kind_counts["spec_mixed"] > 0
        # spec_mixed steps count toward the stall-free ratio
        assert eng.obs.mixed_step_ratio() > 0
        alloc = eng.scheduler.allocator
        assert alloc.num_free == alloc.num_pages - 1

    def test_seeded_sampled_step_grouping_independent(self):
        """Seeded verify keys derive from (seed, position) and a greedy
        draft model's proposals are state-deterministic, so HOW steps
        group (verify slices sharing a chunk's step vs pure spec steps)
        must not change a seeded stream byte-for-byte. (Seeded spec vs
        NON-spec is distribution-equal, not byte-equal — accept/resample
        consumes different randomness than a direct draw; the chi-square
        pin in test_spec_decode covers that contract.)"""
        sp = SamplingParams(max_tokens=16, temperature=0.8, seed=11)
        prompts = [REPETITIVE * 3, list(REPETITIVE)]
        ref = [o.output_token_ids for o in
               make_engine(True, mixed=False, max_prefill=32,
                           draft="debug-tiny",
                           draft_params=_PARAMS).generate(prompts, sp)]
        eng = make_engine(True, mixed=True, max_prefill=32,
                          draft="debug-tiny", draft_params=_PARAMS)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        assert eng.obs.step_kind_counts["spec_mixed"] > 0

    def test_abort_mid_chunk_with_spec_rows(self):
        """Aborting the mid-chunk head while verify slices share its steps
        frees exactly the chunk's pages; the surviving spec rows keep
        decoding to completion."""
        eng = make_engine(True, mixed=True, max_prefill=32,
                          draft="debug-tiny", draft_params=_PARAMS)
        sp = SamplingParams(max_tokens=24, temperature=0.0)
        eng.add_request("a", list(REPETITIVE), sp)
        for _ in range(6):
            eng.step()
        free0 = eng.scheduler.allocator.num_free
        eng.add_request("long", REPETITIVE * 3, sp)
        eng.step()                          # chunk rides a (spec_)mixed step
        head = eng.scheduler.waiting[0]
        assert head.request_id == "long" and head.num_prefilled > 0
        held = len(head.pages)
        free_mid = eng.scheduler.allocator.num_free
        assert held > 0
        assert eng.abort_request("long")
        assert eng.scheduler.allocator.num_free == free_mid + held
        while eng.has_unfinished_requests():
            eng.step()
        alloc = eng.scheduler.allocator
        assert alloc.num_free == alloc.num_pages - 1
        assert free0 <= alloc.num_free


class TestSpecCLIHygiene:
    """Argparse hygiene: spec knobs without --enable-spec-decode are loud
    CLI errors (the --quant-group-size pattern — a swallowed knob means
    the operator believes speculation is configured while the engine
    serves plain decode)."""

    @pytest.mark.parametrize("argv", [
        ["--num-speculative-tokens", "4"],
        ["--spec-draft-model", "tinyllama-1.1b"],
        ["--spec-adaptive-k"],
        ["--spec-k-max", "8"],
    ])
    def test_spec_flags_require_enable_spec_decode(self, argv):
        from kubernetes_gpu_cluster_tpu.serving.api_server import main
        with pytest.raises(SystemExit) as e:
            main(["--model", "debug-tiny"] + argv)
        assert e.value.code == 2

    def test_draft_weights_require_draft_model(self):
        from kubernetes_gpu_cluster_tpu.serving.api_server import main
        with pytest.raises(SystemExit) as e:
            main(["--model", "debug-tiny", "--enable-spec-decode",
                  "--spec-draft-weights", "/tmp/nope"])
        assert e.value.code == 2

    def test_k_max_requires_adaptive_k(self):
        """Without the controller the ladder ceiling has no consumer —
        silently raising the STATIC draft length would double verify
        compute behind the operator's back."""
        from kubernetes_gpu_cluster_tpu.serving.api_server import main
        with pytest.raises(SystemExit) as e:
            main(["--model", "debug-tiny", "--enable-spec-decode",
                  "--spec-k-max", "8"])
        assert e.value.code == 2


class TestSpecDraftObservability:
    def test_current_k_gauge_and_draft_counters(self):
        eng = make_engine(True, draft="debug-tiny", draft_params=_PARAMS)
        text = "\n".join(eng.obs.render_prometheus())
        # fresh spec-on engine: gauge present at the static k, counters 0
        assert "kgct_spec_current_k 4" in text
        assert "kgct_spec_draft_tokens_total 0" in text
        assert "kgct_spec_draft_seconds" in text
        eng.generate([list(REPETITIVE)],
                     SamplingParams(max_tokens=16, temperature=0.0))
        text = "\n".join(eng.obs.render_prometheus())
        assert "kgct_spec_draft_tokens_total 0" not in text
        assert eng.obs.spec_draft_tokens > 0

    def test_current_k_absent_when_spec_off(self):
        eng = make_engine(False)
        text = "\n".join(eng.obs.render_prometheus())
        assert "kgct_spec_current_k" not in text
        assert "kgct_spec_draft_tokens_total 0" in text   # zero-safe

    def test_spec_trace_events_carry_phase_attribution(self):
        eng = make_engine(True, draft="debug-tiny", draft_params=_PARAMS)
        eng.generate([list(REPETITIVE)],
                     SamplingParams(max_tokens=16, temperature=0.0))
        evs = [e for e in eng.obs.tracer.events() if e.kind == "spec"]
        assert evs
        assert "draft_ms" in evs[0].args and "verify_ms" in evs[0].args
