"""End-to-end engine tests: continuous batching, stops, determinism,
preemption — automated versions of the reference's manual serving smoke
checks (SURVEY §4)."""

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams


def make_engine(eos=None, num_pages=128, max_seqs=8, **model_over):
    cfg = EngineConfig(
        model=get_model_config("debug-tiny", **model_over),
        cache=CacheConfig(page_size=8, num_pages=num_pages),
        scheduler=SchedulerConfig(
            max_num_seqs=max_seqs, max_prefill_tokens=256,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(32, 64, 128, 256)))
    return LLMEngine(cfg, eos_token_id=eos)


def test_greedy_matches_teacher_forcing():
    """Engine greedy output must equal the oracle: repeatedly full-prefill the
    growing sequence and take argmax — validates paged decode against dense
    attention through the whole engine path."""
    import jax.numpy as jnp
    from tests.test_model import _prefill_whole

    eng = make_engine()
    prompt = [5, 99, 23, 44, 17]
    n_gen = 10
    out = eng.generate([prompt], SamplingParams(max_tokens=n_gen, temperature=0.0))[0]

    cfg = eng.model_config
    seq = list(prompt)
    expected = []
    for _ in range(n_gen):
        logits, _, _ = _prefill_whole(cfg, eng.params, seq)
        nxt = int(np.argmax(np.asarray(logits)))
        expected.append(nxt)
        seq.append(nxt)
    assert out.output_token_ids == expected


def test_multiple_requests_interleaved():
    eng = make_engine()
    prompts = [[1, 2, 3], [10, 11, 12, 13, 14, 15, 16], [7]]
    outs = eng.generate(prompts, SamplingParams(max_tokens=6, temperature=0.0))
    assert all(len(o.output_token_ids) == 6 for o in outs)
    assert all(o.finish_reason == "length" for o in outs)
    # All KV pages returned after completion.
    assert eng.scheduler.allocator.num_free == eng.scheduler.allocator.num_pages - 1


def test_eos_stop():
    eng = make_engine()
    # Find which token greedy decoding emits first, then declare it EOS.
    probe = eng.generate([[3, 1, 4]], SamplingParams(max_tokens=1, temperature=0.0))[0]
    eos = probe.output_token_ids[0]
    eng2 = make_engine(eos=eos)
    out = eng2.generate([[3, 1, 4]], SamplingParams(max_tokens=50, temperature=0.0))[0]
    assert out.finish_reason == "stop"
    assert out.output_token_ids[-1] == eos and len(out.output_token_ids) == 1
    out = eng2.generate([[3, 1, 4]], SamplingParams(max_tokens=5, temperature=0.0,
                                                    ignore_eos=True))[0]
    assert out.finish_reason == "length" and len(out.output_token_ids) == 5


def test_sampling_params_validation():
    with pytest.raises(ValueError):
        SamplingParams(max_tokens=0)
    with pytest.raises(ValueError):
        SamplingParams(temperature=-1)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)


def test_seed_reproducible_across_engines_and_batchmates():
    """vLLM per-request seed semantics: same prompt + same seed => same
    tokens, independent of the engine's global PRNG state, batch position,
    or window boundaries. Different seeds diverge."""
    prompt = [5, 9, 2, 7]
    p42 = SamplingParams(max_tokens=12, temperature=1.0, seed=42)
    eng = make_engine()
    outs = eng.generate([prompt, prompt, prompt],
                        [p42, p42, SamplingParams(max_tokens=12,
                                                  temperature=1.0, seed=7)])
    assert outs[0].output_token_ids == outs[1].output_token_ids
    assert outs[0].output_token_ids != outs[2].output_token_ids

    eng2 = make_engine()       # fresh engine, different global key state
    eng2.generate([[1, 2]], SamplingParams(max_tokens=3, temperature=1.0))
    again = eng2.generate([prompt], p42)[0]
    assert again.output_token_ids == outs[0].output_token_ids


def test_frequency_penalty_suppresses_repeats():
    """Near-greedy sampling with a strong frequency penalty: every
    repetition costs 2.0 logits, far above debug-tiny's logit gaps, so the
    output cannot dwell on one token; counts must persist across chained
    decode windows (window=4 < max_tokens=16)."""
    eng = make_engine()
    prompt = [3, 1, 4]
    base = eng.generate([prompt], SamplingParams(
        max_tokens=16, temperature=0.01, seed=0))[0]
    pen = eng.generate([prompt], SamplingParams(
        max_tokens=16, temperature=0.01, seed=0,
        frequency_penalty=2.0))[0]
    counts = {}
    for t in pen.output_token_ids:
        counts[t] = counts.get(t, 0) + 1
    assert max(counts.values()) <= 3, (pen.output_token_ids, counts)
    assert len(set(pen.output_token_ids)) > len(set(base.output_token_ids)) \
        or base.output_token_ids == pen.output_token_ids


def test_preempted_seeded_penalized_output_unchanged():
    """Recompute-preemption must not change seeded+penalized results: the
    re-prefill's sampling point applies the same output-token penalties
    (built on-device from the re-prefilled batch) and the same seeded keys
    as the uninterrupted run (regression: penalties were skipped at the
    prefill sampling point)."""
    prompts = [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]]
    params = [SamplingParams(max_tokens=16, temperature=0.8, seed=11,
                             frequency_penalty=1.5, presence_penalty=0.5),
              SamplingParams(max_tokens=16, temperature=0.8, seed=22,
                             frequency_penalty=1.5),
              SamplingParams(max_tokens=16, temperature=0.0)]
    big = make_engine(num_pages=128, max_seqs=4)
    small = make_engine(num_pages=8, max_seqs=4)
    outs_big = big.generate(prompts, params)
    outs_small = small.generate(prompts, params)
    assert small.scheduler.num_preemptions > 0
    for a, b in zip(outs_big, outs_small):
        assert a.output_token_ids == b.output_token_ids


def test_preempted_penalized_chunked_reprefill_exact():
    """When a preempted penalized+seeded sequence's prompt+outputs exceed
    the prefill budget, the re-prefill takes the CHUNKED path — whose
    penalty histogram comes from a host resync of the full output history,
    so outputs must still match the unpressured run exactly (regression:
    the chunked path used to count only the final chunk's in-batch
    tokens)."""
    from kubernetes_gpu_cluster_tpu.config import (
        CacheConfig, EngineConfig, SchedulerConfig, get_model_config)

    def engine(num_pages):
        return LLMEngine(EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=8, num_pages=num_pages),
            scheduler=SchedulerConfig(
                max_num_seqs=4, max_prefill_tokens=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(16,))))

    prompts = [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]]
    params = [SamplingParams(max_tokens=20, temperature=0.8, seed=11,
                             frequency_penalty=1.5, presence_penalty=0.5),
              SamplingParams(max_tokens=20, temperature=0.8, seed=22,
                             frequency_penalty=1.5),
              SamplingParams(max_tokens=20, temperature=0.0)]
    big, small = engine(128), engine(9)
    outs_big = big.generate(prompts, params)
    outs_small = small.generate(prompts, params)
    assert small.scheduler.num_preemptions > 0
    for a, b in zip(outs_big, outs_small):
        assert a.output_token_ids == b.output_token_ids


def test_penalty_params_validated():
    with pytest.raises(ValueError):
        SamplingParams(presence_penalty=3.0)
    with pytest.raises(ValueError):
        SamplingParams(frequency_penalty=-2.5)
    with pytest.raises(ValueError):
        SamplingParams(seed="abc")
    # OpenAI accepts any integer seed (negative/64-bit are folded to 31
    # bits at batch-assembly time).
    assert SamplingParams(seed=-1).seed == -1


def test_logit_bias_forces_and_bans_tokens():
    """OpenAI logit_bias semantics: +100 effectively forces a token, -100
    bans it — across prefill (first token) AND decode windows, greedy and
    sampled dispatch paths."""
    eng = make_engine()
    prompt = [3, 1, 4]
    forced = eng.generate([prompt], SamplingParams(
        max_tokens=6, temperature=0.0, logit_bias={7: 100.0}))[0]
    assert forced.output_token_ids == [7] * 6

    greedy = eng.generate([prompt], SamplingParams(
        max_tokens=4, temperature=0.0))[0]
    banned_tok = greedy.output_token_ids[0]
    banned = eng.generate([prompt], SamplingParams(
        max_tokens=4, temperature=0.0, logit_bias={banned_tok: -100.0}))[0]
    assert banned.output_token_ids[0] != banned_tok

    sampled = eng.generate([prompt], SamplingParams(
        max_tokens=6, temperature=1.0, seed=1, logit_bias={9: 100.0}))[0]
    assert sampled.output_token_ids == [9] * 6

    # out-of-vocab ids are rejected at submission, not silently dropped
    with pytest.raises(ValueError, match="out of range"):
        eng.add_request("bad", prompt, SamplingParams(
            logit_bias={10 ** 6: -100.0}))


def test_top_logprobs_alternatives():
    """logprobs=N alternatives: the top list contains the chosen token for
    greedy rows (argmax == top-1), logprobs are sorted descending, and the
    record spans prefill + chained decode windows."""
    import math
    eng = make_engine()
    out = eng.generate([[3, 1, 4]], SamplingParams(
        max_tokens=6, temperature=0.0, logprobs=True, top_logprobs=3))[0]
    tops = out.output_top_logprobs
    assert len(tops) == 6
    for token, top, lp in zip(out.output_token_ids, tops,
                              out.output_logprobs):
        assert len(top) == 3
        ids = [t for t, _ in top]
        lps = [v for _, v in top]
        assert token == ids[0]          # greedy chose the argmax
        assert lps == sorted(lps, reverse=True)
        assert math.isclose(lps[0], lp, rel_tol=1e-5)

    with pytest.raises(ValueError):
        SamplingParams(top_logprobs=6)
    with pytest.raises(ValueError):
        SamplingParams(top_logprobs=2)   # requires logprobs


def test_logit_bias_validation():
    with pytest.raises(ValueError):
        SamplingParams(logit_bias=[1, 2])
    with pytest.raises(ValueError):
        SamplingParams(logit_bias="abc")
    with pytest.raises(ValueError):
        SamplingParams(logit_bias={5: 101.0})
    with pytest.raises(ValueError):
        SamplingParams(logit_bias={-2: 1.0})
    with pytest.raises(ValueError):
        SamplingParams(logit_bias={"x": 1.0})
    with pytest.raises(ValueError):
        SamplingParams(logit_bias={i: 1.0 for i in range(301)})
    # string keys (json) are coerced
    assert SamplingParams(logit_bias={"5": 1}).logit_bias == {5: 1.0}


def test_stochastic_sampling_runs():
    eng = make_engine()
    outs = eng.generate([[1, 2, 3]] * 2,
                        SamplingParams(max_tokens=8, temperature=0.9, top_k=20, top_p=0.9))
    assert all(len(o.output_token_ids) == 8 for o in outs)


def test_preemption_under_memory_pressure():
    """Tiny page pool forces recompute-preemption; all sequences must still
    finish correctly (the engine-level reset-then-converge property)."""
    eng = make_engine(num_pages=12, max_seqs=4)  # 11 usable pages of 8 tokens
    prompts = [[i, i + 1, i + 2, i + 3] for i in range(4)]
    outs = eng.generate(prompts, SamplingParams(max_tokens=24, temperature=0.0))
    assert all(len(o.output_token_ids) == 24 for o in outs)
    assert eng.scheduler.num_preemptions > 0
    assert eng.scheduler.allocator.num_free == eng.scheduler.allocator.num_pages - 1


def test_preempted_greedy_output_unchanged():
    """Recompute-preemption must not change greedy results vs an unpressured
    run of the same request."""
    prompts = [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]]
    big = make_engine(num_pages=128, max_seqs=4)
    small = make_engine(num_pages=8, max_seqs=4)  # 7 usable pages for 3 seqs
    outs_big = big.generate(prompts, SamplingParams(max_tokens=16, temperature=0.0))
    outs_small = small.generate(prompts, SamplingParams(max_tokens=16, temperature=0.0))
    assert small.scheduler.num_preemptions > 0
    for a, b in zip(outs_big, outs_small):
        assert a.output_token_ids == b.output_token_ids


def test_abort():
    eng = make_engine()
    eng.add_request("keep", [1, 2, 3], SamplingParams(max_tokens=4, temperature=0.0))
    eng.add_request("kill", [4, 5, 6], SamplingParams(max_tokens=4, temperature=0.0))
    assert eng.abort_request("kill")
    assert not eng.abort_request("missing")
    done = []
    while eng.has_unfinished_requests():
        done += [o.request_id for o in eng.step() if o.finished]
    assert done == ["keep"]


def test_prompt_too_long_rejected():
    eng = make_engine()
    with pytest.raises(ValueError, match="exceeds"):
        eng.add_request("x", list(range(1000)))
    # The rejected request's trace span must be CLOSED (arrival + abort) —
    # an unpaired open would render as running forever in /debug/trace.
    kinds = [e.kind for e in eng.obs.tracer.events() if e.request_id == "x"]
    assert kinds == ["arrival", "abort"]


class TestDecodeWindowEquivalence:
    def test_windowed_decode_matches_single_step(self):
        """Greedy generation must be identical for decode_window=1 and =4:
        the on-device autoregressive scan is semantically the same loop."""
        import jax
        from kubernetes_gpu_cluster_tpu.models import llama as model_lib
        base = dict(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=4, num_pages=64))
        params = model_lib.init_params(base["model"], jax.random.key(7))
        prompts = [[1, 5, 9, 2], [3, 3, 7]]
        sp = SamplingParams(temperature=0.0, max_tokens=10)

        outs = {}
        for w in (1, 4):
            cfg = EngineConfig(
                scheduler=SchedulerConfig(
                    max_num_seqs=4, max_prefill_tokens=64,
                    decode_buckets=(2, 4), prefill_buckets=(16, 32),
                    decode_window=w),
                **base)
            eng = LLMEngine(cfg, params=params)
            outs[w] = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert outs[1] == outs[4]


class TestLogprobs:
    def test_greedy_logprobs_match_forward(self):
        """The engine's per-token logprob record must match the log-softmax
        of an independent forward pass for the first sampled token, align
        1:1 with output tokens, and be non-positive throughout."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from kubernetes_gpu_cluster_tpu.config import (CacheConfig,
                                                       EngineConfig,
                                                       SchedulerConfig,
                                                       get_model_config)
        from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
        from kubernetes_gpu_cluster_tpu.models import llama as model_lib

        cfg = EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=16, num_pages=33),
            scheduler=SchedulerConfig(max_num_seqs=2, max_prefill_tokens=64,
                                      decode_buckets=(1, 2),
                                      prefill_buckets=(64,)))
        params = model_lib.init_params(cfg.model, jax.random.key(0))
        eng = LLMEngine(cfg, params=params)
        prompt = [1, 5, 9, 2]
        out = eng.generate([prompt], SamplingParams(
            temperature=0.0, max_tokens=4, logprobs=True))[0]
        assert len(out.output_logprobs) == len(out.output_token_ids)
        assert all(lp <= 0.0 for lp in out.output_logprobs)

        # Manual prefill forward -> log-softmax at the sampled token.
        T = 64
        toks = np.zeros(T, np.int32)
        toks[:len(prompt)] = prompt
        seg = np.where(np.arange(T) < len(prompt), 0, -1).astype(np.int32)
        pos = np.where(np.arange(T) < len(prompt),
                       np.arange(T), 0).astype(np.int32)
        slots = np.where(np.arange(T) < len(prompt),
                         16 + np.arange(T), np.arange(T) % 16).astype(np.int32)
        meta = model_lib.PrefillMeta(
            seg_ids=jnp.asarray(seg), positions=jnp.asarray(pos),
            slot_mapping=jnp.asarray(slots),
            logits_indices=jnp.asarray([len(prompt) - 1], jnp.int32))
        kv = allocate_kv_cache(cfg.model, cfg.cache, 33)
        hidden, _, _ = model_lib.forward_prefill(params, cfg.model,
                                                 jnp.asarray(toks), meta, kv)
        logits = model_lib.compute_logits(params, cfg.model, hidden)[0]
        assert out.output_token_ids[0] == int(jnp.argmax(logits))
        ref_lp = float(jax.nn.log_softmax(logits)[out.output_token_ids[0]])
        np.testing.assert_allclose(out.output_logprobs[0], ref_lp,
                                   rtol=1e-4, atol=1e-4)
