"""Test configuration: run every test on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness (tp/pp/
dp/ep) is validated on XLA's host-platform virtual devices instead — the
fake-backend test strategy the reference lacked entirely (SURVEY §4: "no
automated tests in the reference").

Note: this sandbox force-registers a TPU backend from sitecustomize, so the
env-var route (JAX_PLATFORMS=cpu) is not enough — we must also flip the jax
config knob before any computation runs.
"""

import os

# Must be set before jax initializes its backends.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"

# -- environment capability gates ---------------------------------------------
# Some tests need jax features this container's jax (0.4.x) does not ship.
# They are ENV gaps, not code regressions — erroring them buries real
# failures in noise, so they skip with an explicit reason instead. The
# capability probe is the top-level ``jax.shard_map`` export (added ~0.6);
# the same jax vintage also lacks the Pallas interpret-mode state-discharge
# rules and CPU multiprocess collectives, so one probe keys all three
# groups. On a jax that has ``jax.shard_map`` everything runs again
# untouched. Recorded in ROADMAP ("tier-1 signal" note).

_HAVE_JAX_SHARD_MAP = hasattr(jax, "shard_map")


def _build_env_gates(have_shard_map: bool) -> dict:
    """(file, test name) -> why this env cannot run it, keyed on the
    unparametrized test function name. A capable env (top-level
    ``jax.shard_map`` present) gates NOTHING — everything runs. Factored
    out so tests/test_conftest_gate.py can pin the gate table and the
    per-class reasons independent of the env actually running the suite."""
    if have_shard_map:
        return {}
    shard_map_reason = (
        "env gap: this jax (%s) has no top-level jax.shard_map (the tp/pp/ep "
        "wrappers call it); pre-existing since the seed" % jax.__version__)
    interpret_reason = (
        "env gap: this jax (%s) lacks Pallas interpret-mode state-discharge "
        "rules (kernel raises NotImplementedError on CPU); pre-existing "
        "since the seed" % jax.__version__)
    multiproc_reason = (
        "env gap: this jaxlib (%s) has no CPU multiprocess collectives "
        "('Multiprocess computations aren't implemented on the CPU "
        "backend'); pre-existing since the seed" % jax.__version__)
    gated = {}
    for _file, _name, _why in [
        ("test_distributed.py", "test_two_process_jax_distributed", multiproc_reason),
        ("test_distributed.py", "test_two_process_full_engine", multiproc_reason),
        ("test_distributed.py", "test_two_process_serving_leader_follower", multiproc_reason),
        ("test_pallas.py", "test_stacked_pool_layer_index", interpret_reason),
        ("test_pallas.py", "test_paged_decode_tp_matches_oracle", shard_map_reason),
        ("test_pallas.py", "test_flash_prefill_tp_matches_oracle", shard_map_reason),
        ("test_pallas.py", "test_engine_decode_via_attn_mesh", shard_map_reason),
        ("test_pallas.py", "test_prefill_history_tp_matches_oracle", shard_map_reason),
        ("test_parallel.py", "test_pp_engine_matches_single_device", shard_map_reason),
        ("test_parallel.py", "test_pp_only_mesh_matches_single_device", shard_map_reason),
        ("test_parallel.py", "test_pp_engine_chunked_prefill", shard_map_reason),
        ("test_parallel.py", "test_moe_block_shard_map_matches_dense", shard_map_reason),
        ("test_parallel.py", "test_pp_prefill_matches_single_device", shard_map_reason),
        ("test_parallel.py", "test_pp_decode_matches_single_device", shard_map_reason),
        ("test_parallel.py", "test_north_star_70b_tp_pp_traces", shard_map_reason),
        ("test_parallel.py", "test_pp_hist_no_layer_stack_gather", shard_map_reason),
    ]:
        gated[(_file, _name)] = _why
    # TestPagedDecodeKernel::test_matches_xla shares a name with other
    # classes' interpret-mode tests that DO pass; key the gated one by its
    # class too.
    gated[("test_pallas.py", "TestPagedDecodeKernel.test_matches_xla")] = \
        interpret_reason
    return gated


_ENV_GATED = _build_env_gates(_HAVE_JAX_SHARD_MAP)


def _apply_env_gates(items, gates) -> list:
    """Add skip markers to exactly the gated items; returns the (item,
    reason) pairs applied. Anything NOT in the gate table is left alone —
    a new failure must FAIL, the gates exist to keep known env gaps from
    burying it in noise (tests/test_conftest_gate.py pins both sides)."""
    import pytest

    applied = []
    for item in items:
        fname = item.path.name if hasattr(item, "path") else item.fspath.basename
        name = item.originalname if getattr(item, "originalname", None) else item.name
        cls = item.cls.__name__ + "." if getattr(item, "cls", None) else ""
        # Class-qualified key wins (disambiguates test_matches_xla, which
        # exists in several kernel classes and only one is env-gated).
        why = gates.get((fname, cls + name)) or gates.get((fname, name))
        if why:
            item.add_marker(pytest.mark.skip(reason=why))
            applied.append((item, why))
    return applied


def pytest_collection_modifyitems(config, items):
    if not _ENV_GATED:
        return
    _apply_env_gates(items, _ENV_GATED)

# -- per-test timeout fallback ----------------------------------------------
# pytest-timeout (wired via pyproject [tool.pytest.ini_options]) is the real
# implementation when installed; this container does not ship it, so a
# minimal SIGALRM fallback enforces the same contract: a regressed hang
# fails ONE test fast (default 300 s, tighter via @pytest.mark.timeout(N))
# instead of eating the whole 870 s tier-1 budget.

try:
    import pytest_timeout  # noqa: F401
    _HAVE_PYTEST_TIMEOUT = True
except ImportError:
    _HAVE_PYTEST_TIMEOUT = False

if not _HAVE_PYTEST_TIMEOUT:
    import signal
    import threading

    import pytest

    _DEFAULT_TIMEOUT_S = 300.0

    @pytest.hookimpl(hookwrapper=True)
    def pytest_runtest_call(item):
        marker = item.get_closest_marker("timeout")
        limit = (float(marker.args[0]) if marker and marker.args
                 else _DEFAULT_TIMEOUT_S)
        # Only the call phase is timed (fixture setup legitimately pays XLA
        # compile time); SIGALRM needs the main thread, like pytest-timeout's
        # signal method.
        if (limit <= 0 or not hasattr(signal, "SIGALRM")
                or threading.current_thread() is not threading.main_thread()):
            yield
            return

        def _on_alarm(signum, frame):
            raise TimeoutError(
                f"test exceeded {limit:.0f}s (conftest SIGALRM fallback; "
                "install pytest-timeout for stack dumps)")

        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.setitimer(signal.ITIMER_REAL, limit)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old)
