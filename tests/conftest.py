"""Test configuration: run every test on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness (tp/pp/
dp/ep) is validated on XLA's host-platform virtual devices instead — the
fake-backend test strategy the reference lacked entirely (SURVEY §4: "no
automated tests in the reference").

Note: this sandbox force-registers a TPU backend from sitecustomize, so the
env-var route (JAX_PLATFORMS=cpu) is not enough — we must also flip the jax
config knob before any computation runs.
"""

import os

# Must be set before jax initializes its backends.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"
