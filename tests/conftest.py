"""Test configuration: run every test on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness (tp/pp/
dp/ep) is validated on XLA's host-platform virtual devices instead — the
fake-backend test strategy the reference lacked entirely (SURVEY §4: "no
automated tests in the reference").

Note: this sandbox force-registers a TPU backend from sitecustomize, so the
env-var route (JAX_PLATFORMS=cpu) is not enough — we must also flip the jax
config knob before any computation runs.
"""

import os

# Must be set before jax initializes its backends.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.device_count() == 8, f"expected 8 virtual CPU devices, got {jax.devices()}"

# -- per-test timeout fallback ----------------------------------------------
# pytest-timeout (wired via pyproject [tool.pytest.ini_options]) is the real
# implementation when installed; this container does not ship it, so a
# minimal SIGALRM fallback enforces the same contract: a regressed hang
# fails ONE test fast (default 300 s, tighter via @pytest.mark.timeout(N))
# instead of eating the whole 870 s tier-1 budget.

try:
    import pytest_timeout  # noqa: F401
    _HAVE_PYTEST_TIMEOUT = True
except ImportError:
    _HAVE_PYTEST_TIMEOUT = False

if not _HAVE_PYTEST_TIMEOUT:
    import signal
    import threading

    import pytest

    _DEFAULT_TIMEOUT_S = 300.0

    @pytest.hookimpl(hookwrapper=True)
    def pytest_runtest_call(item):
        marker = item.get_closest_marker("timeout")
        limit = (float(marker.args[0]) if marker and marker.args
                 else _DEFAULT_TIMEOUT_S)
        # Only the call phase is timed (fixture setup legitimately pays XLA
        # compile time); SIGALRM needs the main thread, like pytest-timeout's
        # signal method.
        if (limit <= 0 or not hasattr(signal, "SIGALRM")
                or threading.current_thread() is not threading.main_thread()):
            yield
            return

        def _on_alarm(signum, frame):
            raise TimeoutError(
                f"test exceeded {limit:.0f}s (conftest SIGALRM fallback; "
                "install pytest-timeout for stack dumps)")

        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.setitimer(signal.ITIMER_REAL, limit)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old)
