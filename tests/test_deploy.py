"""Deployment-surface tests: the reference's values schema must render.

The done-criterion from the build plan: all nine reference
``values-01-minimal-example*.yaml`` files render valid manifest sets. Those
tests are gated on the reference checkout being present; schema-level
behavior (TPU resource mapping, anti-affinity passthrough, raySpec ->
StatefulSet + jax.distributed coordinator, router) is covered by inline
fixtures so the suite stays self-contained elsewhere.
"""

from __future__ import annotations

import copy
import glob
import os
import re

import pytest
import yaml

from kubernetes_gpu_cluster_tpu.deploy import render_values

REFERENCE_GLOB = "/root/reference/values-01-minimal-example*.yaml"

VALUES = {
    "servingEngineSpec": {
        "runtimeClassName": "crun",
        "modelSpec": [{
            "name": "qwen3",
            "repository": "vllm/vllm-openai",
            "tag": "v0.8.4",
            "modelURL": "/models/Qwen2.5-7B",
            "replicaCount": 2,
            "requestCPU": 6,
            "requestMemory": "8Gi",
            "requestGPU": 2,
            "shmSize": "10Gi",
            "env": [{"name": "X", "value": "y"}],
            "vllmConfig": {
                "tensorParallelSize": 2,
                "gpuMemoryUtilization": 0.95,
                "maxModelLen": 2048,
                "extraArgs": ["--dtype", "float16", "--enforce-eager"],
            },
            "nodeSelector": {"kgct.io/tpu": "true"},
            "affinity": {"podAntiAffinity": {"x": 1}},
            "topologySpreadConstraints": [{"maxSkew": 1}],
            "extraVolumes": [{"name": "local-models",
                              "hostPath": {"path": "/models/Qwen2.5-7B",
                                           "type": "Directory"}}],
            "extraVolumeMounts": [{"name": "local-models",
                                   "mountPath": "/models/Qwen2.5-7B",
                                   "readOnly": True}],
        }],
    },
}

DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _validate(manifests: dict) -> None:
    assert manifests, "no manifests rendered"
    for fname, m in manifests.items():
        assert m.get("apiVersion") and m.get("kind"), fname
        name = m["metadata"]["name"]
        assert DNS1123.match(name), f"{fname}: bad name {name}"
        yaml.safe_dump(m)   # serializable
        if m["kind"] in ("Deployment", "StatefulSet"):
            tmpl = m["spec"]["template"]
            sel = m["spec"]["selector"]["matchLabels"]
            labels = tmpl["metadata"]["labels"]
            assert sel.items() <= labels.items(), f"{fname}: selector mismatch"
            containers = tmpl["spec"]["containers"]
            assert containers and containers[0]["image"], fname
        if m["kind"] == "Service":
            assert m["spec"]["ports"], fname


def test_engine_deployment_shape():
    ms = render_values(copy.deepcopy(VALUES))
    _validate(ms)
    dep = ms["qwen3-engine-deployment.yaml"]
    assert dep["spec"]["replicas"] == 2
    pod = dep["spec"]["template"]["spec"]
    c = pod["containers"][0]
    # requestGPU -> google.com/tpu (the device plugin's resource)
    assert c["resources"]["requests"]["google.com/tpu"] == 2
    assert c["resources"]["limits"]["google.com/tpu"] == 2
    # vllmConfig mapped onto the engine CLI
    args = c["args"]
    assert args[args.index("--tensor-parallel-size") + 1] == "2"
    assert args[args.index("--hbm-utilization") + 1] == "0.95"
    assert args[args.index("--max-model-len") + 1] == "2048"
    assert "--enforce-eager" in args          # extraArgs passthrough
    # local model path -> weights + tokenizer flags
    assert args[args.index("--weights") + 1] == "/models/Qwen2.5-7B"
    # scheduling controls pass through
    assert pod["nodeSelector"] == {"kgct.io/tpu": "true"}
    assert "podAntiAffinity" in pod["affinity"]
    assert pod["topologySpreadConstraints"]
    assert pod["runtimeClassName"] == "crun"
    # hostPath model volume + shm volume mounted
    vol_names = {v["name"] for v in pod["volumes"]}
    assert {"local-models", "dshm"} <= vol_names
    mount_paths = {m["mountPath"] for m in c["volumeMounts"]}
    assert {"/models/Qwen2.5-7B", "/dev/shm"} <= mount_paths


def test_mixed_batch_knobs_map_to_engine_flags():
    """Mixed batching is the engine DEFAULT now: absent/true render no
    flag; an explicit ``enableMixedBatch: false`` renders the
    --disable-mixed-batch opt-out; decodePriorityTokenBudget renders
    whenever set."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["enableMixedBatch"] = True
    cfg["decodePriorityTokenBudget"] = 1536
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--enable-mixed-batch" not in args       # default, no flag needed
    assert "--disable-mixed-batch" not in args
    assert args[args.index("--decode-priority-token-budget") + 1] == "1536"
    # default values file: mixing on by engine default, nothing rendered
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--enable-mixed-batch" not in args
    assert "--disable-mixed-batch" not in args
    # explicit opt-out renders the disable flag
    values = copy.deepcopy(VALUES)
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "enableMixedBatch"] = False
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--disable-mixed-batch" in args


def test_spec_decode_knobs_map_to_engine_flags():
    """vllmConfig.enableSpecDecode / numSpeculativeTokens render to the API
    server's --enable-spec-decode / --num-speculative-tokens (the
    speculative-decoding deployment surface); absent renders nothing."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["enableSpecDecode"] = True
    cfg["numSpeculativeTokens"] = 6
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--enable-spec-decode" in args
    assert args[args.index("--num-speculative-tokens") + 1] == "6"
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--enable-spec-decode" not in args
    assert "--num-speculative-tokens" not in args


def test_spec_draft_model_knobs_map_to_engine_flags():
    """vllmConfig.specDraftModel / specAdaptiveK / specKMax render to
    --spec-draft-model / --spec-adaptive-k / --spec-k-max; absent renders
    nothing (n-gram drafting, static k stay the engine defaults)."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["enableSpecDecode"] = True
    cfg["specDraftModel"] = "tinyllama-1.1b"
    cfg["specAdaptiveK"] = True
    cfg["specKMax"] = 8
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--spec-draft-model") + 1] == "tinyllama-1.1b"
    assert "--spec-adaptive-k" in args
    assert args[args.index("--spec-k-max") + 1] == "8"
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    for flag in ("--spec-draft-model", "--spec-adaptive-k", "--spec-k-max"):
        assert flag not in args


def test_spec_draft_model_invalid_combos_fail_render():
    """Draft-model/adaptive-k knobs without enableSpecDecode fail the
    RENDER (the CLI-hygiene mirror: a silently dropped knob means the
    operator believes speculation is tuned while the pod serves plain
    decode), and so do multihost/pp topologies (no spec forward path
    under pp meshes; the draft model cannot join SPMD lockstep)."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["specDraftModel"] = "tinyllama-1.1b"
    with pytest.raises(ValueError, match="enableSpecDecode"):
        render_values(values)
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["enableSpecDecode"] = True
    cfg["specAdaptiveK"] = True
    cfg["pipelineParallelSize"] = 2
    with pytest.raises(ValueError, match="multihost"):
        render_values(values)
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["enableSpecDecode"] = True
    cfg["specKMax"] = 8             # ceiling without the controller
    with pytest.raises(ValueError, match="specAdaptiveK"):
        render_values(values)


def test_swap_space_knob_maps_to_engine_flag():
    """vllmConfig.swapSpaceGB renders to the API server's --swap-space-gb
    (the two-tier KV cache's deployment surface, vLLM swapSpace parity);
    absent renders nothing — swap stays off by default."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["swapSpaceGB"] = 4
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--swap-space-gb") + 1] == "4"
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--swap-space-gb" not in args


def test_qos_tiers_render_golden():
    """vllmConfig.qosTiers (+qosDefaultTier) render to one validated
    --qos-tiers CLI JSON on BOTH the engine and the router (the two layers
    must resolve tiers identically); absent renders nothing (QoS off,
    byte-identical manifests)."""
    import json as _json
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["qosTiers"] = [
        {"name": "interactive", "weight": 4, "priority": 10,
         "maxConcurrent": 64, "ttftBudgetMs": 1000,
         "users": ["alice"]},
        {"name": "batch", "weight": 1},
    ]
    cfg["qosDefaultTier"] = "interactive"
    ms = render_values(values)
    eargs = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    rargs = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    ejson = eargs[eargs.index("--qos-tiers") + 1]
    # Golden pin of the rendered CLI JSON (the engine/router contract).
    assert _json.loads(ejson) == {
        "interactive": {"weight": 4.0, "priority": 10,
                        "max_concurrent": 64, "ttft_budget_ms": 1000.0,
                        "users": ["alice"]},
        "batch": {"weight": 1.0, "priority": 0},
    }
    assert eargs[eargs.index("--qos-default-tier") + 1] == "interactive"
    # Router carries the SAME table + default.
    assert rargs[rargs.index("--qos-tiers") + 1] == ejson
    assert rargs[rargs.index("--qos-default-tier") + 1] == "interactive"
    # Absent -> nothing rendered on either layer.
    ms = render_values(copy.deepcopy(VALUES))
    for f in ("qwen3-engine-deployment.yaml", "router-deployment.yaml"):
        args = ms[f]["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--qos-tiers" not in args


def test_qos_tiers_validation_fails_render():
    """Duplicate tier names, unknown keys, a qosDefaultTier naming an
    unconfigured tier, and a routerSpec/vllmConfig table conflict all fail
    the RENDER — never the pod at start."""
    def with_cfg(**kw):
        values = copy.deepcopy(VALUES)
        values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"].update(kw)
        return values

    with pytest.raises(ValueError, match="duplicate qosTiers name"):
        render_values(with_cfg(qosTiers=[{"name": "a"}, {"name": "a"}]))
    with pytest.raises(ValueError, match="unknown key"):
        render_values(with_cfg(qosTiers=[{"name": "a", "wieght": 2}]))
    with pytest.raises(ValueError, match="not a configured tier"):
        render_values(with_cfg(qosTiers=[{"name": "a"}],
                               qosDefaultTier="zz"))
    with pytest.raises(ValueError, match="qosDefaultTier requires"):
        render_values(with_cfg(qosDefaultTier="a"))
    with pytest.raises(ValueError, match="weight"):
        render_values(with_cfg(qosTiers=[{"name": "a", "weight": 0}]))
    with pytest.raises(ValueError, match="LIST of tenant keys"):
        # YAML scalar users would list() into characters
        render_values(with_cfg(qosTiers=[{"name": "a", "users": "alice"}]))
    values = with_cfg(qosTiers=[{"name": "a"}])
    values["routerSpec"] = {"qosTiers": [{"name": "b"}]}
    with pytest.raises(ValueError, match="contradicts"):
        render_values(values)


def test_quantization_knobs_map_to_engine_flags():
    """vllmConfig.quantization / quantGroupSize render to the API server's
    --quantization / --quant-group-size (the weight-only quant ladder's
    deployment surface); absent renders nothing."""
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["quantization"] = "int4"
    cfg["quantGroupSize"] = 64
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--quantization") + 1] == "int4"
    assert args[args.index("--quant-group-size") + 1] == "64"
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--quantization" not in args
    assert "--quant-group-size" not in args


def test_engine_pod_graceful_drain_contract():
    """The deploy renderer must give the SIGTERM drain room to work: a
    preStop sleep so endpoint removal outruns the signal, and a termination
    grace period that outlasts the engine's default drain_grace_s (120 s)."""
    ms = render_values(copy.deepcopy(VALUES))
    pod = ms["qwen3-engine-deployment.yaml"]["spec"]["template"]["spec"]
    c = pod["containers"][0]
    pre_stop = c["lifecycle"]["preStop"]["exec"]["command"]
    assert "sleep" in " ".join(pre_stop)
    assert pod["terminationGracePeriodSeconds"] > 120
    # The multihost StatefulSet template carries the same contract.
    values = copy.deepcopy(VALUES)
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "pipelineParallelSize"] = 2
    ms = render_values(values)
    sts_pod = ms["qwen3-engine-statefulset.yaml"]["spec"]["template"]["spec"]
    assert sts_pod["terminationGracePeriodSeconds"] > 120
    assert "lifecycle" in sts_pod["containers"][0]


def test_migration_budget_derives_termination_grace():
    """vllmConfig.migrationBudgetSeconds: live KV migration makes the
    SIGTERM drain transfer-bound, so the pod's SIGKILL deadline derives
    from the (much tighter) migration budget — budget + preStop sleep (5)
    + exit margin (10) — instead of the decode-bound 150 s default, and
    the engine's wait-it-out fallback bound rides along as
    --drain-grace-s. Golden pins across the three topologies."""
    # Deployment topology: grace derived, no per-pod DNS -> no --peer-pool.
    values = copy.deepcopy(VALUES)
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "migrationBudgetSeconds"] = 20
    ms = render_values(values)
    pod = ms["qwen3-engine-deployment.yaml"]["spec"]["template"]["spec"]
    assert pod["terminationGracePeriodSeconds"] == 35
    args = pod["containers"][0]["args"]
    assert args[args.index("--drain-grace-s") + 1] == "20"
    assert "--peer-pool" not in args
    # Prefix-affinity StatefulSet: pool siblings have stable DNS, so the
    # drain-push allowlist names them.
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "routingPolicy"] = "prefix-affinity"
    ms = render_values(values)
    pod = ms["qwen3-engine-statefulset.yaml"]["spec"]["template"]["spec"]
    assert pod["terminationGracePeriodSeconds"] == 35
    args = pod["containers"][0]["args"]
    assert args[args.index("--peer-pool") + 1] == ",".join(
        f"http://kgct-qwen3-engine-{i}.kgct-qwen3-engine-hl:8000"
        for i in range(2))
    # Disaggregated: only the DECODE pool holds streams — it gets the
    # sibling allowlist; the prefill pool gets the budget alone.
    ms = render_values(_disagg_values(
        vllmConfig={"migrationBudgetSeconds": 30}))
    for role, expect_peers in (("decode", True), ("prefill", False)):
        pod = ms[f"m-{role}-engine-statefulset.yaml"]["spec"]["template"][
            "spec"]
        assert pod["terminationGracePeriodSeconds"] == 45
        args = pod["containers"][0]["args"]
        assert args[args.index("--drain-grace-s") + 1] == "30"
        if expect_peers:
            assert args[args.index("--peer-pool") + 1] == ",".join(
                f"http://kgct-m-decode-engine-{i}"
                f".kgct-m-decode-engine-hl:8000" for i in range(3))
        else:
            assert "--peer-pool" not in args
    # Unset keeps the decode-bound default (byte-stable manifests).
    ms = render_values(copy.deepcopy(VALUES))
    pod = ms["qwen3-engine-deployment.yaml"]["spec"]["template"]["spec"]
    assert pod["terminationGracePeriodSeconds"] == 150
    assert "--drain-grace-s" not in pod["containers"][0]["args"]
    # A budget the drain cannot use fails the render, not the pod.
    bad = copy.deepcopy(VALUES)
    bad["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "migrationBudgetSeconds"] = 0
    with pytest.raises(ValueError, match="migrationBudgetSeconds"):
        render_values(bad)


def test_router_fronts_models():
    ms = render_values(copy.deepcopy(VALUES))
    router = ms["router-deployment.yaml"]
    args = router["spec"]["template"]["spec"]["containers"][0]["args"]
    replicas = args[args.index("--replicas") + 1]
    assert replicas == "http://kgct-qwen3-engine-svc:8000"
    # Default policy renders NO routing flags: the router's own
    # least-inflight default applies and pre-affinity manifests are
    # byte-stable.
    assert "--routing-policy" not in args
    assert "--affinity-prefix-len" not in args
    assert "--balance-factor" not in args
    svc = ms["router-svc.yaml"]
    assert svc["metadata"]["name"] == "kgct-router-service"
    assert svc["spec"]["ports"][0]["port"] == 80


def test_routing_policy_knobs_render_to_router_args():
    """routerSpec routing knobs (and the values-schema-compatible
    vllmConfig.routingPolicy spelling) render end-to-end into the router
    Deployment's args; unknown policies fail the RENDER."""
    values = copy.deepcopy(VALUES)
    values["routerSpec"] = {"routingPolicy": "prefix-affinity",
                            "affinityPrefixLen": 48, "balanceFactor": 1.25}
    ms = render_values(values)
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--routing-policy") + 1] == "prefix-affinity"
    assert args[args.index("--affinity-prefix-len") + 1] == "48"
    assert args[args.index("--balance-factor") + 1] == "1.25"
    # vllmConfig spelling on the first modelSpec works too
    values = copy.deepcopy(VALUES)
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "routingPolicy"] = "prefix-affinity"
    ms = render_values(values)
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--routing-policy") + 1] == "prefix-affinity"
    # explicit least-inflight renders the flag (operator pinned it)
    values = copy.deepcopy(VALUES)
    values["routerSpec"] = {"routingPolicy": "least-inflight"}
    ms = render_values(values)
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--routing-policy") + 1] == "least-inflight"
    assert "qwen3-engine-deployment.yaml" in ms    # no StatefulSet switch
    values = copy.deepcopy(VALUES)
    values["routerSpec"] = {"routingPolicy": "sticky-random"}
    with pytest.raises(ValueError, match="routingPolicy"):
        render_values(values)


def test_routing_policy_honored_and_validated_on_any_model_spec():
    """There is ONE router: vllmConfig.routingPolicy works from any
    modelSpec entry (not just the first), a typo on any entry fails the
    render, and two entries naming different policies is a contradiction."""
    def two_models(cfg_a, cfg_b):
        return {"servingEngineSpec": {"modelSpec": [
            {"name": "a", "modelURL": "/models/a", "requestGPU": 1,
             "vllmConfig": cfg_a},
            {"name": "b", "modelURL": "/models/b", "requestGPU": 1,
             "vllmConfig": cfg_b}]}}

    ms = render_values(two_models({}, {"routingPolicy": "prefix-affinity"}))
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--routing-policy") + 1] == "prefix-affinity"
    assert "a-engine-statefulset.yaml" in ms      # both models switch
    with pytest.raises(ValueError, match="not a known policy"):
        render_values(two_models({}, {"routingPolicy": "prefix-afinity"}))
    with pytest.raises(ValueError, match="conflicting"):
        render_values(two_models({"routingPolicy": "least-inflight"},
                                 {"routingPolicy": "prefix-affinity"}))
    # ...and the same contradiction across LAYERS fails too (routerSpec
    # silently winning would deploy a router the modelSpec believes is
    # cache-affine).
    vals = two_models({}, {"routingPolicy": "prefix-affinity"})
    vals["routerSpec"] = {"routingPolicy": "least-inflight"}
    with pytest.raises(ValueError, match="contradicts"):
        render_values(vals)
    # agreement across layers is not a contradiction
    vals = two_models({}, {"routingPolicy": "prefix-affinity"})
    vals["routerSpec"] = {"routingPolicy": "prefix-affinity"}
    assert render_values(vals)


def test_prefix_affinity_renders_per_replica_addressing():
    """Prefix-affinity needs the ring to own PODS, not a Service VIP
    (kube-proxy's random pod choice behind one URL would re-scatter
    sessions): replicaCount renders end-to-end as a StatefulSet with a
    headless Service and one stable per-pod URL per replica in the
    router's --replicas."""
    values = copy.deepcopy(VALUES)
    values["routerSpec"] = {"routingPolicy": "prefix-affinity"}
    ms = render_values(values)
    _validate(ms)
    assert "qwen3-engine-deployment.yaml" not in ms
    sts = ms["qwen3-engine-statefulset.yaml"]
    assert sts["spec"]["replicas"] == 2            # replicaCount
    assert sts["spec"]["serviceName"] == "kgct-qwen3-engine-hl"
    assert sts["spec"]["podManagementPolicy"] == "Parallel"
    hl = ms["qwen3-engine-headless-svc.yaml"]
    assert hl["spec"]["clusterIP"] == "None"
    assert hl["spec"]["publishNotReadyAddresses"] is True
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    replicas = args[args.index("--replicas") + 1]
    assert replicas == (
        "http://kgct-qwen3-engine-0.kgct-qwen3-engine-hl:8000,"
        "http://kgct-qwen3-engine-1.kgct-qwen3-engine-hl:8000")
    # The ordinary per-model Service still renders for non-router clients.
    assert "qwen3-engine-svc.yaml" in ms
    # Multihost (pp > 1) keeps its rank-0 Service as ONE routing target
    # even under affinity: peer ranks must never receive client traffic.
    values = copy.deepcopy(VALUES)
    values["routerSpec"] = {"routingPolicy": "prefix-affinity"}
    values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "pipelineParallelSize"] = 2
    ms = render_values(values)
    args = ms["router-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--replicas") + 1] == \
        "http://kgct-qwen3-engine-svc:8000"


def test_scrape_annotations_engine_and_router():
    """Engine pods AND the router pod carry prometheus.io scrape
    annotations: the router's /metrics is the fleet aggregation point —
    its own series (affinity hit ratio, per-replica locality gauges,
    trace/metrics scrape-error counters) exist nowhere else, so an
    annotation-based Prometheus must discover it too. (Engine families the
    router re-exports are replica-labeled; dashboards aggregate per scrape
    job to avoid double counting — README "Observability".)"""
    ms = render_values(copy.deepcopy(VALUES))
    eng_meta = ms["qwen3-engine-deployment.yaml"]["spec"]["template"]["metadata"]
    ann = eng_meta["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/port"] == "8000"
    assert ann["prometheus.io/path"] == "/metrics"
    router_meta = ms["router-deployment.yaml"]["spec"]["template"]["metadata"]
    rann = router_meta["annotations"]
    assert rann["prometheus.io/scrape"] == "true"
    assert rann["prometheus.io/port"] == "8080"
    assert rann["prometheus.io/path"] == "/metrics"


def test_rayspec_renders_statefulset_with_coordinator():
    values = copy.deepcopy(VALUES)
    spec = values["servingEngineSpec"]["modelSpec"][0]
    spec["vllmConfig"] = {"pipelineParallelSize": 2}
    spec["raySpec"] = {"headNode": {"requestCPU": 1, "requestMemory": "10Gi",
                                    "requestGPU": 1}}
    ms = render_values(values)
    _validate(ms)
    assert "qwen3-engine-statefulset.yaml" in ms
    sts = ms["qwen3-engine-statefulset.yaml"]
    assert sts["spec"]["replicas"] == 2          # one pod per PP rank
    assert sts["spec"]["serviceName"] == "kgct-qwen3-engine-hl"
    c = sts["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e for e in c["env"]}
    assert env["KGCT_COORDINATOR"]["value"] == (
        "kgct-qwen3-engine-0.kgct-qwen3-engine-hl:8476")
    assert env["KGCT_NUM_PROCESSES"]["value"] == "2"
    assert "--distributed" in c["args"]
    hl = ms["qwen3-engine-headless-svc.yaml"]
    assert hl["spec"]["clusterIP"] == "None"
    ports = {p["name"]: p["port"] for p in hl["spec"]["ports"]}
    assert ports["coordinator"] == 8476
    # chips per pod still tensor-shard under PP (no idle chips)
    assert c["args"][c["args"].index("--tensor-parallel-size") + 1] == "2"
    # client traffic must only reach rank 0 (it drives the global-mesh step)
    svc = ms["qwen3-engine-svc.yaml"]
    assert svc["spec"]["selector"]["apps.kubernetes.io/pod-index"] == "0"


def test_single_host_service_has_no_pod_index_pin():
    ms = render_values(copy.deepcopy(VALUES))
    svc = ms["qwen3-engine-svc.yaml"]
    assert "apps.kubernetes.io/pod-index" not in svc["spec"]["selector"]


def test_single_chip_defaults_no_tp_flag():
    values = copy.deepcopy(VALUES)
    spec = values["servingEngineSpec"]["modelSpec"][0]
    spec["requestGPU"] = 1
    del spec["vllmConfig"]
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--tensor-parallel-size" not in args


def test_multi_chip_defaults_tp_to_chip_count():
    values = copy.deepcopy(VALUES)
    spec = values["servingEngineSpec"]["modelSpec"][0]
    del spec["vllmConfig"]          # no explicit TP; 2 chips requested
    ms = render_values(values)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args[args.index("--tensor-parallel-size") + 1] == "2"


@pytest.mark.parametrize("path", sorted(glob.glob(REFERENCE_GLOB)) or
                         [pytest.param(None, marks=pytest.mark.skip(
                             reason="reference checkout not present"))])
def test_reference_values_files_render(path):
    """Every one of the reference's nine values files renders a valid set."""
    with open(path) as f:
        values = yaml.safe_load(f)
    ms = render_values(values)
    _validate(ms)
    spec = values["servingEngineSpec"]["modelSpec"][0]
    kind = ("StatefulSet" if (spec.get("raySpec") or
                              (spec.get("vllmConfig") or {})
                              .get("pipelineParallelSize", 1) > 1)
            else "Deployment")
    workloads = [m for m in ms.values() if m["kind"] == kind]
    assert workloads, f"{path}: no {kind} rendered"
    c = workloads[0]["spec"]["template"]["spec"]["containers"][0]
    if spec.get("requestGPU"):
        assert c["resources"]["requests"]["google.com/tpu"] == \
            spec["requestGPU"]
    assert any(m["kind"] == "Service" for m in ms.values())

class TestHelmChart:
    """Helm-workflow parity (deploy/chart.py): the emitted chart must be a
    structurally valid helm v2 chart whose templates are exactly the
    renderer's manifests — `helm install/upgrade/rollback` then manages
    releases natively (reference workflow old_README.md:1079-1082)."""

    VALUES = {
        "servingEngineSpec": {
            "runtimeClassName": "crun",
            "modelSpec": [{
                "name": "opt125m",
                "modelURL": "facebook/opt-125m",
                "replicaCount": 2,
                "requestCPU": 6,
                "requestMemory": "16Gi",
                "requestGPU": 1,
            }],
        },
    }

    def test_emit_chart_structure(self, tmp_path):
        from kubernetes_gpu_cluster_tpu.deploy.chart import emit_chart
        from kubernetes_gpu_cluster_tpu.deploy.render import render_values

        files = emit_chart(self.VALUES, str(tmp_path))
        assert "Chart.yaml" in files and "values.yaml" in files

        chart = yaml.safe_load((tmp_path / "Chart.yaml").read_text())
        assert chart["apiVersion"] == "v2"
        assert chart["name"] == "kgct-stack"
        assert chart["version"] and chart["appVersion"]

        # values.yaml embeds the operator's input verbatim.
        assert yaml.safe_load((tmp_path / "values.yaml").read_text()) == self.VALUES

        # templates/ == renderer output, byte-for-byte content parity.
        manifests = render_values(self.VALUES)
        tdir = tmp_path / "templates"
        emitted = {p.name for p in tdir.iterdir() if p.suffix == ".yaml"}
        assert emitted == set(manifests)
        for fname, manifest in manifests.items():
            assert yaml.safe_load((tdir / fname).read_text()) == manifest
        assert (tdir / "NOTES.txt").read_text().startswith("kgct-stack deployed")

    @pytest.mark.parametrize("path", sorted(glob.glob(REFERENCE_GLOB)) or
                             [pytest.param(None, marks=pytest.mark.skip(
                                 reason="reference checkout not present"))])
    def test_reference_values_files_emit_charts(self, path, tmp_path):
        """Every reference values file must produce an installable chart."""
        from kubernetes_gpu_cluster_tpu.deploy.chart import emit_chart
        with open(path) as f:
            values = yaml.safe_load(f)
        files = emit_chart(values, str(tmp_path))
        assert "Chart.yaml" in files
        assert any(f.startswith("templates/") and f.endswith(".yaml")
                   for f in files)

    def test_cli_emit_chart(self, tmp_path):
        from kubernetes_gpu_cluster_tpu.deploy.render import main
        vf = tmp_path / "values.yaml"
        vf.write_text(yaml.safe_dump(self.VALUES))
        out = tmp_path / "chart"
        main(["-f", str(vf), "--emit-chart", str(out)])
        assert (out / "Chart.yaml").exists()
        assert (out / "templates" / "opt125m-engine-deployment.yaml").exists()

    def test_reemit_removes_stale_templates(self, tmp_path):
        """Re-emitting into the same dir must drop manifests for removed
        models — stale files would keep deploying them on helm upgrade."""
        from kubernetes_gpu_cluster_tpu.deploy.chart import emit_chart
        two = {"servingEngineSpec": {"modelSpec": [
            {"name": "a", "modelURL": "debug-tiny", "requestGPU": 1},
            {"name": "b", "modelURL": "debug-moe", "requestGPU": 1}]}}
        emit_chart(two, str(tmp_path))
        assert (tmp_path / "templates" / "b-engine-deployment.yaml").exists()
        one = {"servingEngineSpec": {"modelSpec": [
            {"name": "a", "modelURL": "debug-tiny", "requestGPU": 1}]}}
        emit_chart(one, str(tmp_path))
        assert not (tmp_path / "templates" / "b-engine-deployment.yaml").exists()
        assert (tmp_path / "templates" / "a-engine-deployment.yaml").exists()

    def test_go_template_braces_escaped(self, tmp_path):
        """Literal '{{' in pass-through values (e.g. a Jinja chat template
        arg) must be emitted as an escaped Go-template action or helm
        install fails to parse the chart."""
        from kubernetes_gpu_cluster_tpu.deploy.chart import emit_chart
        vals = {"servingEngineSpec": {"modelSpec": [{
            "name": "a", "modelURL": "debug-tiny", "requestGPU": 1,
            "env": [{"name": "CHAT_TEMPLATE",
                     "value": "{{ messages[0].content }}"}]}]}}
        emit_chart(vals, str(tmp_path))
        text = (tmp_path / "templates" / "a-engine-deployment.yaml").read_text()
        assert "{{ messages" not in text
        assert '{{"{{"}}' in text


def test_model_url_validation():
    """Render-time modelURL guardrails (VERDICT r4 missing #1/#2): unknown
    architecture families fail the RENDER with actionable guidance; the
    reference's own minimal file (opt-125m) renders and its model is now a
    servable preset; family-known hub ids render with a warning."""
    import pytest
    from kubernetes_gpu_cluster_tpu.deploy.render import render_values

    def values(url, **spec):
        return {"servingEngineSpec": {"modelSpec": [
            {"name": "m", "modelURL": url, **spec}]}}

    with pytest.raises(ValueError, match="supported architecture family"):
        render_values(values("bigscience/bloom-560m"))
    with pytest.raises(ValueError, match="missing modelURL"):
        render_values(values(""))
    # the reference's minimal example model: renders AND resolves to a preset
    out = render_values(values("facebook/opt-125m"))
    assert any("deployment" in k for k in out)
    # family-supported, preset-less id still renders (pre-staged-weights story)
    assert render_values(values("Qwen/Qwen3-0.6B"))
    # absolute path (pre-staged checkpoint) passes through untouched
    assert render_values(values("/models/llama-3-8b"))


def _disagg_values(**spec_extra):
    spec = {"name": "m", "modelURL": "tinyllama-1.1b",
            "prefillReplicas": 2, "decodeReplicas": 3}
    spec.update(spec_extra)
    return {"servingEngineSpec": {"modelSpec": [spec]}}


def test_disagg_renders_role_split_statefulsets():
    """prefillReplicas/decodeReplicas -> one StatefulSet + headless Service
    per phase pool, pods started with --role, and the router wired with
    the decode pool as --replicas plus the prefill pool as
    --prefill-replicas (golden pins of the disaggregated topology)."""
    ms = render_values(_disagg_values())
    _validate(ms)
    for role, count in (("prefill", 2), ("decode", 3)):
        sts = ms[f"m-{role}-engine-statefulset.yaml"]
        assert sts["kind"] == "StatefulSet"
        assert sts["spec"]["replicas"] == count
        assert sts["spec"]["serviceName"] == f"kgct-m-{role}-engine-hl"
        args = sts["spec"]["template"]["spec"]["containers"][0]["args"]
        assert args[args.index("--role") + 1] == role
        if role == "decode":
            # KV-pull allowlist: decode pods may only fetch handoffs from
            # their spec's prefill pods (SSRF guard for per-pod DNS).
            assert args[args.index("--prefill-pool") + 1] == ",".join(
                f"http://kgct-m-prefill-engine-{i}"
                f".kgct-m-prefill-engine-hl:8000" for i in range(2))
        else:
            assert "--prefill-pool" not in args
        hl = ms[f"m-{role}-engine-headless-svc.yaml"]
        assert hl["spec"]["clusterIP"] == "None"
        assert hl["spec"]["publishNotReadyAddresses"] is True
    # No plain Deployment/Service for a disaggregated spec: the router
    # addresses pods directly in both pools.
    assert "m-engine-deployment.yaml" not in ms
    rargs = ms["router-deployment.yaml"]["spec"]["template"]["spec"][
        "containers"][0]["args"]
    assert rargs[rargs.index("--replicas") + 1] == ",".join(
        f"http://kgct-m-decode-engine-{i}.kgct-m-decode-engine-hl:8000"
        for i in range(3))
    assert rargs[rargs.index("--prefill-replicas") + 1] == ",".join(
        f"http://kgct-m-prefill-engine-{i}.kgct-m-prefill-engine-hl:8000"
        for i in range(2))


def test_disagg_validation():
    import pytest

    # One-sided pools cannot be routed.
    with pytest.raises(ValueError, match="set together"):
        render_values({"servingEngineSpec": {"modelSpec": [
            {"name": "m", "modelURL": "tinyllama-1.1b",
             "prefillReplicas": 2}]}})
    with pytest.raises(ValueError, match=">= 1"):
        render_values(_disagg_values(prefillReplicas=0))
    # Disaggregation does not compose with multihost (SPMD lockstep).
    with pytest.raises(ValueError, match="multihost"):
        render_values(_disagg_values(
            vllmConfig={"pipelineParallelSize": 2}))
    # ...nor with a multi-modelSpec stack: the one router has ONE prefill
    # ring, while each decode pod's --prefill-pool allowlist covers only
    # its own spec — cross-spec picks would silently degrade to local
    # recompute on every affected prefix.
    vals = _disagg_values()
    vals["servingEngineSpec"]["modelSpec"].append(
        {"name": "other", "modelURL": "tinyllama-1.1b", "replicaCount": 1})
    with pytest.raises(ValueError, match="multi-modelSpec"):
        render_values(vals)


def test_default_render_has_no_role_flag():
    """role: both is the engine default and renders NO flag — a
    non-disaggregated spec's manifests are byte-identical to before."""
    ms = render_values({"servingEngineSpec": {"modelSpec": [
        {"name": "m", "modelURL": "tinyllama-1.1b"}]}})
    args = ms["m-engine-deployment.yaml"]["spec"]["template"]["spec"][
        "containers"][0]["args"]
    assert "--role" not in args
    assert not any(f.endswith("hpa.yaml") for f in ms)


def test_autoscaling_renders_hpa_golden():
    """autoscaling.enabled -> an autoscaling/v2 HPA off the landed
    autoscaler signals: queue-wait p90 + shed rate as Pods metrics, the
    SLO attainment gauge documented as the (inverse) guardrail, and
    scale-down stabilized against ring-remap flapping."""
    ms = render_values({"servingEngineSpec": {"modelSpec": [
        {"name": "m", "modelURL": "tinyllama-1.1b", "replicaCount": 2,
         "autoscaling": {"enabled": True, "minReplicas": 2,
                         "maxReplicas": 9,
                         "targetQueueWaitSeconds": 0.25}}]}})
    _validate(ms)
    hpa = ms["m-engine-hpa.yaml"]
    assert hpa["apiVersion"] == "autoscaling/v2"
    assert hpa["kind"] == "HorizontalPodAutoscaler"
    spec = hpa["spec"]
    assert spec["scaleTargetRef"] == {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "name": "kgct-m-engine"}
    assert (spec["minReplicas"], spec["maxReplicas"]) == (2, 9)
    metrics = {m["pods"]["metric"]["name"]:
               m["pods"]["target"]["averageValue"]
               for m in spec["metrics"]}
    assert metrics == {"kgct_queue_wait_seconds_p90": "250m",
                       "kgct_requests_shed_per_second": "100m"}
    assert spec["behavior"]["scaleDown"]["stabilizationWindowSeconds"] == 300
    ann = hpa["metadata"]["annotations"]
    assert "kgct_slo_ttft_attainment_ratio" in ann["kgct.io/slo-guardrail"]
    assert "histogram_quantile" in ann["kgct.io/adapter-rule-queue-wait"]
    # maxReplicas defaults from replicaCount when omitted.
    ms2 = render_values({"servingEngineSpec": {"modelSpec": [
        {"name": "m", "modelURL": "tinyllama-1.1b", "replicaCount": 3,
         "autoscaling": {"enabled": True}}]}})
    assert ms2["m-engine-hpa.yaml"]["spec"]["maxReplicas"] == 6


def test_autoscaling_rejected_for_static_pod_list_topologies():
    """HPA + a STATIC per-pod router replica list is a contradiction: the
    scaler would add pods the ring never owns. Fails the RENDER with
    guidance for prefix-affinity, disaggregated, and multihost specs."""
    import pytest

    with pytest.raises(ValueError, match="Deployment topology"):
        render_values({"servingEngineSpec": {"modelSpec": [
            {"name": "m", "modelURL": "tinyllama-1.1b",
             "vllmConfig": {"routingPolicy": "prefix-affinity"},
             "autoscaling": {"enabled": True}}]}})
    with pytest.raises(ValueError, match="Deployment topology"):
        render_values(_disagg_values(autoscaling={"enabled": True}))
    with pytest.raises(ValueError, match="multihost"):
        render_values({"servingEngineSpec": {"modelSpec": [
            {"name": "m", "modelURL": "tinyllama-1.1b",
             "vllmConfig": {"pipelineParallelSize": 2},
             "autoscaling": {"enabled": True}}]}})


def test_fleet_prefix_cache_knob():
    """vllmConfig.fleetPrefixCache: --fleet-prefix-cache plus the
    --peer-pool pull/spill allowlist, on per-pod-addressed topologies
    only — plain-Service Deployments refuse the render with guidance
    (same pattern as affinity routing), as do multihost groups and specs
    without the local prefix cache the fleet cache federates."""
    # Prefix-affinity StatefulSet: flag + sibling allowlist.
    values = copy.deepcopy(VALUES)
    cfg = values["servingEngineSpec"]["modelSpec"][0]["vllmConfig"]
    cfg["fleetPrefixCache"] = True
    cfg["enablePrefixCaching"] = True
    cfg["routingPolicy"] = "prefix-affinity"
    ms = render_values(values)
    args = ms["qwen3-engine-statefulset.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--fleet-prefix-cache" in args
    assert args[args.index("--peer-pool") + 1] == ",".join(
        f"http://kgct-qwen3-engine-{i}.kgct-qwen3-engine-hl:8000"
        for i in range(2))
    # With a migration budget too, --peer-pool renders exactly ONCE.
    values2 = copy.deepcopy(values)
    values2["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "migrationBudgetSeconds"] = 20
    ms = render_values(values2)
    args = ms["qwen3-engine-statefulset.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert args.count("--peer-pool") == 1
    assert "--fleet-prefix-cache" in args
    # Disaggregated pools are per-pod-addressed: renders without affinity.
    ms = render_values(_disagg_values(
        vllmConfig={"fleetPrefixCache": True, "enablePrefixCaching": True}))
    args = ms["m-decode-engine-statefulset.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--fleet-prefix-cache" in args
    assert args[args.index("--peer-pool") + 1] == ",".join(
        f"http://kgct-m-decode-engine-{i}.kgct-m-decode-engine-hl:8000"
        for i in range(3))
    # Plain-Service Deployment: refused with guidance.
    bad = copy.deepcopy(VALUES)
    bad["servingEngineSpec"]["modelSpec"][0]["vllmConfig"].update(
        {"fleetPrefixCache": True, "enablePrefixCaching": True})
    with pytest.raises(ValueError, match="per-pod"):
        render_values(bad)
    # Without the local prefix cache there is nothing to federate.
    bad = copy.deepcopy(VALUES)
    bad["servingEngineSpec"]["modelSpec"][0]["vllmConfig"].update(
        {"fleetPrefixCache": True, "routingPolicy": "prefix-affinity"})
    with pytest.raises(ValueError, match="enablePrefixCaching"):
        render_values(bad)
    # Multihost: SPMD lockstep cannot import peer KV on rank 0 alone.
    bad = {"servingEngineSpec": {"modelSpec": [
        {"name": "m", "modelURL": "tinyllama-1.1b",
         "vllmConfig": {"pipelineParallelSize": 2,
                        "fleetPrefixCache": True,
                        "enablePrefixCaching": True}}]}}
    with pytest.raises(ValueError, match="multihost"):
        render_values(bad)
    # Off (absent) keeps manifests byte-stable: no flag anywhere.
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--fleet-prefix-cache" not in args


def test_integrity_checks_knob():
    """vllmConfig.integrityChecks: default ON renders nothing (wire
    bytes byte-identical to the pre-integrity encoders only when
    explicitly opted OUT); only the literal ``false`` renders
    --no-integrity-checks."""
    ms = render_values(copy.deepcopy(VALUES))
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--no-integrity-checks" not in args
    on = copy.deepcopy(VALUES)
    on["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "integrityChecks"] = True
    ms = render_values(on)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--no-integrity-checks" not in args
    off = copy.deepcopy(VALUES)
    off["servingEngineSpec"]["modelSpec"][0]["vllmConfig"][
        "integrityChecks"] = False
    ms = render_values(off)
    args = ms["qwen3-engine-deployment.yaml"][
        "spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--no-integrity-checks" in args
