"""Chunked prefill + prefill admission fairness.

Chunked prefill: a prompt longer than max_prefill_tokens streams through in
solo chunks that attend to the sequence's committed pool history
(ops.attention.prefill_history_attention_xla). The bar: IDENTICAL greedy
output to an engine with a budget big enough to prefill in one step.

Fairness: a blocked large prompt at the queue head must not stall small
prompts behind it (bounded lookahead, no reordering).
"""

import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams


def _engine(max_prefill_tokens, max_num_seqs=4, num_pages=129, mixed=False):
    # These are LEGACY-policy pins (solo-chunk admission, lookahead,
    # preemption ordering), so mixing is pinned off explicitly now that
    # mixed batching is the SchedulerConfig default; the mixed-policy
    # equivalents live in tests/test_mixed_batch.py.
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=num_pages),
        scheduler=SchedulerConfig(
            max_num_seqs=max_num_seqs, max_prefill_tokens=max_prefill_tokens,
            decode_buckets=(1, 2, 4), prefill_buckets=(32, 64, 128, 256),
            mixed_batch_enabled=mixed))
    return LLMEngine(cfg)


def test_long_prompt_chunks_and_matches_unchunked():
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 500, 150).tolist()   # 150 > budget 32
    params = SamplingParams(max_tokens=8, temperature=0.0)

    ref_eng = _engine(max_prefill_tokens=256)
    ref = ref_eng.generate([prompt], params)[0].output_token_ids

    eng = _engine(max_prefill_tokens=32)
    out = eng.generate([prompt], params)[0].output_token_ids
    assert out == ref, (out, ref)
    # it actually chunked: 150 tokens / 32-budget => ceil = 5 prefill steps
    assert eng.scheduler.num_preemptions == 0


def test_chunk_progress_and_solo_admission():
    eng = _engine(max_prefill_tokens=32)
    eng.add_request("long", list(range(1, 81)), SamplingParams(max_tokens=4))
    eng.add_request("short", [1, 2, 3], SamplingParams(max_tokens=4))
    sched = eng.scheduler

    b1 = sched.schedule()
    assert b1.kind == "prefill" and b1.hist_len == 0 and b1.partial
    assert b1.seqs[0].request_id == "long"
    assert b1.num_seqs == 1                      # solo
    assert b1.seqs[0].num_prefilled == 32
    np.testing.assert_array_equal(b1.positions[:32], np.arange(32))

    b2 = sched.schedule()
    assert b2.hist_len == 32 and b2.partial
    np.testing.assert_array_equal(b2.positions[:32], np.arange(32, 64))

    b3 = sched.schedule()
    assert b3.hist_len == 64 and not b3.partial  # final chunk: 80 - 64 = 16
    assert b3.seqs[0].status.value == "running"
    # the short request is next (was behind the chunking head, not starved)
    b4 = sched.schedule()
    assert b4.kind == "prefill" and b4.seqs[0].request_id == "short"


def test_multiple_long_prompts_e2e():
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 500, n).tolist() for n in (100, 40, 70)]
    params = SamplingParams(max_tokens=6, temperature=0.0)
    ref = [o.output_token_ids for o in
           _engine(max_prefill_tokens=256).generate(prompts, params)]
    got = [o.output_token_ids for o in
           _engine(max_prefill_tokens=32).generate(prompts, params)]
    assert got == ref


def test_abort_mid_chunk_releases_pages():
    eng = _engine(max_prefill_tokens=32)
    eng.add_request("long", list(range(1, 101)), SamplingParams(max_tokens=4))
    free0 = eng.scheduler.allocator.num_free
    eng.step()                                   # first chunk: pages held
    assert eng.scheduler.allocator.num_free < free0
    assert eng.abort_request("long")
    assert eng.scheduler.allocator.num_free == free0


def test_lookahead_admits_small_behind_blocked_large():
    """Pool sized so the large head prompt cannot get pages while small ones
    can: the small ones must still be admitted (no head-of-line blocking),
    and the queue order must be preserved for the head."""
    eng = _engine(max_prefill_tokens=64, num_pages=9)  # 8 usable pages
    sched = eng.scheduler
    # head needs 8 pages; can_allocate(8) is True only when pool empty —
    # admit a small seq first to occupy pages.
    eng.add_request("small-0", [1, 2, 3], SamplingParams(max_tokens=2))
    b = sched.schedule()
    assert b.seqs[0].request_id == "small-0"     # takes 1 page
    eng2_prompt = list(range(1, 62))             # needs 8 pages > 7 free
    eng.add_request("big", eng2_prompt, SamplingParams(max_tokens=2))
    eng.add_request("small-1", [4, 5], SamplingParams(max_tokens=2))
    b2 = sched.schedule()
    assert b2 is not None, "small-1 was starved behind the blocked big prompt"
    assert [s.request_id for s in b2.seqs] == ["small-1"]
    # big is still at the queue head, unreordered
    assert sched.waiting[0].request_id == "big"


def test_blocked_chunk_head_does_not_starve_small():
    """A chunkable head that cannot get pages falls through to lookahead
    admission; once pages free, the head gets first claim."""
    eng = _engine(max_prefill_tokens=32, num_pages=9)   # 8 usable pages
    sched = eng.scheduler
    eng.add_request("small-0", [1, 2, 3], SamplingParams(max_tokens=2))
    assert sched.schedule().seqs[0].request_id == "small-0"  # holds 1 page
    # chunkable head: first chunk needs 4 pages; only fits while <=4 free...
    # fill more pages so the chunk is blocked
    eng.add_request("eater", list(range(1, 30)), SamplingParams(max_tokens=2))
    b = sched.schedule()
    assert b.seqs[0].request_id == "eater"               # 4 more pages
    eng.add_request("big", list(range(1, 60)), SamplingParams(max_tokens=2))
    eng.add_request("small-1", [7, 8], SamplingParams(max_tokens=2))
    # big's first chunk needs 4 pages, 3 free -> blocked; small-1 (1 page) goes
    b2 = sched.schedule()
    assert b2 is not None and b2.seqs[0].request_id == "small-1"
    assert sched.waiting[0].request_id == "big"          # still the head


def test_preemption_never_displaces_mid_chunk_head():
    """A preempted victim must slot in BEHIND a mid-chunk head — displacing
    it would strand its held pages (scheduler deadlock)."""
    eng = _engine(max_prefill_tokens=32, num_pages=17)
    sched = eng.scheduler
    eng.add_request("victim", [1, 2], SamplingParams(max_tokens=2))
    assert sched.schedule().seqs[0].request_id == "victim"   # now running
    eng.add_request("big", list(range(1, 70)), SamplingParams(max_tokens=2))
    b = sched.schedule()
    assert b.partial and sched.waiting[0].request_id == "big"  # mid-chunk head
    assert sched._preempt_youngest()
    # the mid-chunk head must still be first; victim slots in behind it
    assert sched.waiting[0].request_id == "big"
    assert sched.waiting[1].request_id == "victim"
