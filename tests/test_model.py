"""Model correctness: paged decode must agree with dense prefill (the
numerical oracle for the whole paged-attention path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.config import CacheConfig, get_model_config
from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
from kubernetes_gpu_cluster_tpu.models import llama as M

PAGE = 8


def _prefill_whole(cfg, params, token_ids, num_pages=64):
    """Run a single-sequence dense prefill; returns last-position logits."""
    kv = allocate_kv_cache(cfg, CacheConfig(page_size=PAGE), num_pages)
    n = len(token_ids)
    pages = list(range(1, 1 + (n + PAGE - 1) // PAGE))
    pos = np.arange(n)
    slots = np.array([pages[p // PAGE] * PAGE + p % PAGE for p in pos], np.int32)
    meta = M.PrefillMeta(
        seg_ids=jnp.zeros(n, jnp.int32),
        positions=jnp.asarray(pos, jnp.int32),
        slot_mapping=jnp.asarray(slots),
        logits_indices=jnp.array([n - 1], jnp.int32))
    hidden, kv, _ = M.forward_prefill(params, cfg, jnp.asarray(token_ids, jnp.int32),
                                      meta, kv, use_pallas=False)
    return M.compute_logits(params, cfg, hidden)[0], kv, pages


@pytest.mark.parametrize("model_name", ["debug-tiny", "debug-moe"])
def test_decode_matches_prefill(model_name):
    """Teacher-forcing oracle: next-token logits from an incremental paged
    decode must match the logits from a full dense prefill of the same
    sequence."""
    cfg = get_model_config(model_name)
    params = M.init_params(cfg, jax.random.key(0))
    rng = np.random.RandomState(0)
    seq = rng.randint(1, cfg.vocab_size, size=13).tolist()

    # Oracle: full prefill of seq -> logits for next token.
    oracle_logits, _, _ = _prefill_whole(cfg, params, seq)

    # Paged path: prefill seq[:-1], then decode seq[-1] against the cache.
    prefix = seq[:-1]
    _, kv, pages = _prefill_whole(cfg, params, prefix)
    n = len(prefix)
    if n % PAGE == 0:
        pages = pages + [max(pages) + 1]
    dmeta = M.DecodeMeta(
        positions=jnp.array([n], jnp.int32),
        slot_mapping=jnp.array([pages[n // PAGE] * PAGE + n % PAGE], jnp.int32),
        page_tables=jnp.asarray([pages], jnp.int32),
        context_lens=jnp.array([n + 1], jnp.int32))
    hidden, kv, _ = M.forward_decode(params, cfg, jnp.array([seq[-1]], jnp.int32),
                                     dmeta, kv, use_pallas=False)
    decode_logits = M.compute_logits(params, cfg, hidden)[0]

    np.testing.assert_allclose(np.asarray(decode_logits), np.asarray(oracle_logits),
                               rtol=2e-4, atol=2e-4)


def test_ragged_prefill_isolation():
    """Tokens in one segment must not attend across segment boundaries: a
    two-sequence ragged batch must produce the same last-token logits as each
    sequence prefilled alone."""
    cfg = get_model_config("debug-tiny")
    params = M.init_params(cfg, jax.random.key(1))
    rng = np.random.RandomState(1)
    s0 = rng.randint(1, cfg.vocab_size, size=6).tolist()
    s1 = rng.randint(1, cfg.vocab_size, size=9).tolist()

    solo0, _, _ = _prefill_whole(cfg, params, s0)
    solo1, _, _ = _prefill_whole(cfg, params, s1)

    kv = allocate_kv_cache(cfg, CacheConfig(page_size=PAGE), 64)
    T = 16  # padded ragged batch
    toks = np.zeros(T, np.int32)
    seg = np.full(T, -1, np.int32)
    pos = np.zeros(T, np.int32)
    slots = np.zeros(T, np.int32)
    i = 0
    logits_idx = []
    for s, sq in enumerate([s0, s1]):
        for p, t in enumerate(sq):
            toks[i] = t; seg[i] = s; pos[i] = p
            slots[i] = (1 + s * 4 + p // PAGE) * PAGE + p % PAGE
            i += 1
        logits_idx.append(i - 1)
    meta = M.PrefillMeta(jnp.asarray(seg), jnp.asarray(pos), jnp.asarray(slots),
                         jnp.asarray(logits_idx, jnp.int32))
    hidden, _, _ = M.forward_prefill(params, cfg, jnp.asarray(toks), meta, kv,
                                     use_pallas=False)
    logits = M.compute_logits(params, cfg, hidden)
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(solo0), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(solo1), rtol=2e-4, atol=2e-4)


def test_qwen_variants_forward():
    """attention_bias (qwen2) and qk_norm+tied-embeddings (qwen3) paths run."""
    for variant in [dict(attention_bias=True), dict(qk_norm=True, tie_word_embeddings=True)]:
        cfg = get_model_config("debug-tiny").replace(**variant)
        params = M.init_params(cfg, jax.random.key(2))
        logits, _, _ = _prefill_whole(cfg, params, [1, 2, 3, 4])
        assert logits.shape == (cfg.vocab_size,)
        assert np.isfinite(np.asarray(logits)).all()
