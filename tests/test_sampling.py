"""Parity tests for ops/sampling.py.

The production `_apply_filters` takes a lax.top_k fast path (TOP_K_CAP wide)
with a runtime fallback to a full [B, V] sort. Both must match REFERENCE_FILTER
— the straightforward one-shared-sort implementation (vLLM's logits-processor
semantics: top-k first, top-p over the renormalized post-top-k distribution) —
bit-for-bit on the filtered logits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_gpu_cluster_tpu.ops.sampling import (
    TOP_K_CAP, TOP_K_CAP_WIDE, _apply_filters, apply_penalties, build_counts,
    bump_counts, row_sample_keys, sample_and_logprobs, sample_tokens,
    token_logprobs)


def reference_filter(scaled, top_k, top_p):
    """The original full-sort implementation, kept verbatim as the oracle."""
    V = scaled.shape[-1]
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    k_thresh = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    pos = jax.lax.broadcasted_iota(jnp.int32, sorted_logits.shape, 1)
    k_sorted = jnp.where(pos < k[:, None], sorted_logits, -jnp.inf)
    sorted_probs = jax.nn.softmax(k_sorted, axis=-1)
    cumsum = jnp.cumsum(sorted_probs, axis=-1)
    keep = jnp.clip(
        jnp.sum(cumsum - sorted_probs < top_p[:, None], axis=-1), 1, V)
    p_thresh = jnp.take_along_axis(k_sorted, (keep - 1)[:, None], axis=-1)
    return jnp.where(scaled < jnp.maximum(k_thresh, p_thresh), -jnp.inf,
                     scaled)


def _peaked_logits(rng, B, V, scale=8.0):
    """Sharply peaked rows so top-p prefixes resolve well inside TOP_K_CAP."""
    logits = rng.standard_normal((B, V)).astype(np.float32)
    peak_cols = rng.integers(0, V, (B, 8))
    for b in range(B):
        logits[b, peak_cols[b]] += scale
    return jnp.asarray(logits)


@pytest.mark.parametrize("top_k,top_p", [
    (20, 1.0),            # top-k only
    (0, 0.9),             # top-p only
    (20, 0.9),            # both
    (TOP_K_CAP, 0.5),     # k exactly at the cap
])
def test_fast_path_matches_reference_peaked(top_k, top_p):
    rng = np.random.default_rng(0)
    B, V = 8, 4096
    scaled = _peaked_logits(rng, B, V)
    tk = jnp.full((B,), top_k, jnp.int32)
    tp = jnp.full((B,), top_p, jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("top_k,top_p", [
    (0, 0.9),             # near-uniform: top-p prefix far wider than the cap
    (TOP_K_CAP + 37, 1.0),  # k beyond the cap
    (500, 0.95),
])
def test_fallback_path_matches_reference_uniform(top_k, top_p):
    rng = np.random.default_rng(1)
    B, V = 8, 4096
    scaled = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32)) * 0.01
    tk = jnp.full((B,), top_k, jnp.int32)
    tp = jnp.full((B,), top_p, jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mixed_rows_match_reference():
    """Heterogeneous per-row params: disabled rows, capped rows, p rows."""
    rng = np.random.default_rng(2)
    B, V = 6, 2048
    scaled = _peaked_logits(rng, B, V)
    tk = jnp.asarray([0, 1, 50, 0, TOP_K_CAP, 7], jnp.int32)
    tp = jnp.asarray([1.0, 1.0, 0.9, 0.5, 0.99, 0.8], jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tied_kth_value_matches_reference():
    """Logits tied with the k-th value must NOT inflate the top-p
    renormalizer on the fast path (regression: a value-threshold mask kept
    both tied logits, changing the kept top-p prefix). Ties are realistic
    with quantized logits."""
    V = 4096
    row = np.full((V,), -10.0, np.float32)
    row[0], row[1], row[2] = 2.0, 1.0, 1.0
    scaled = jnp.asarray(np.stack([row, row]))
    tk = jnp.asarray([2, 2], jnp.int32)
    tp = jnp.asarray([0.7, 0.7], jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # p(top)=0.731 >= 0.7 under the exact 2-token renormalizer => keep only
    # the argmax.
    assert np.isfinite(np.asarray(got)[0]).sum() == 1


def test_wide_tier_matches_reference():
    """top_k in (TOP_K_CAP, TOP_K_CAP_WIDE]: the second-tier lax.top_k
    window (which replaced the immediate full-vocab sort on big-vocab
    models) must match the full-sort oracle exactly. V > TOP_K_CAP_WIDE so
    the wide tier is actually live, heterogeneous rows so tier-1-resolvable
    rows ride along through the batch-global tier-2 cond."""
    rng = np.random.default_rng(7)
    B, V = 6, TOP_K_CAP_WIDE + 512
    scaled = _peaked_logits(rng, B, V)
    tk = jnp.asarray([300, 1000, TOP_K_CAP_WIDE, TOP_K_CAP + 1, 0, 50],
                     jnp.int32)
    tp = jnp.asarray([1.0, 0.95, 0.5, 1.0, 0.9, 0.9], jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_beyond_wide_tier_falls_back_to_exact_sort():
    """Rows the wide window cannot resolve (top_k > TOP_K_CAP_WIDE, or a
    near-uniform top-p prefix wider than it) still take the exact full-sort
    path and match the oracle."""
    rng = np.random.default_rng(8)
    B, V = 4, TOP_K_CAP_WIDE + 512
    scaled = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32)) * 0.01
    tk = jnp.asarray([TOP_K_CAP_WIDE + 100, 0, 40, 2500], jnp.int32)
    tp = jnp.asarray([1.0, 0.95, 0.9, 0.99], jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_small_vocab_uses_full_sort():
    rng = np.random.default_rng(3)
    B, V = 4, TOP_K_CAP // 2   # V <= cap: static full-sort path
    scaled = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32))
    tk = jnp.asarray([0, 3, 10, V], jnp.int32)
    tp = jnp.asarray([0.9, 1.0, 0.5, 0.7], jnp.float32)
    got = _apply_filters(scaled, tk, tp)
    want = reference_filter(scaled, tk, tp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sample_tokens_greedy_rows_exact():
    rng = np.random.default_rng(4)
    B, V = 8, 512
    logits = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32))
    temp = jnp.asarray([0.0, 1.0] * (B // 2), jnp.float32)
    toks = sample_tokens(logits, jax.random.PRNGKey(0), temp,
                         jnp.zeros((B,), jnp.int32), jnp.ones((B,), jnp.float32))
    greedy = jnp.argmax(logits, axis=-1)
    np.testing.assert_array_equal(np.asarray(toks[::2]), np.asarray(greedy[::2]))


def test_sample_tokens_respects_top_k_1():
    """top_k=1 at temperature>0 must always return the argmax."""
    rng = np.random.default_rng(5)
    B, V = 8, 4096
    logits = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32))
    toks = sample_tokens(logits, jax.random.PRNGKey(7),
                         jnp.ones((B,), jnp.float32),
                         jnp.ones((B,), jnp.int32),
                         jnp.ones((B,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, axis=-1)))


def test_penalty_counts_and_application():
    """build_counts / bump_counts / apply_penalties implement the OpenAI
    presence+frequency formula over output-token occurrence counts."""
    out = jnp.asarray([[3, 3, 5, -1], [-1, -1, -1, -1]], jnp.int32)
    counts = build_counts(out, vocab_size=8)
    expect = np.zeros((2, 8), np.int32)
    expect[0, 3], expect[0, 5] = 2, 1
    np.testing.assert_array_equal(np.asarray(counts), expect)

    counts = bump_counts(counts, jnp.asarray([5, 0], jnp.int32))
    expect[0, 5], expect[1, 0] = 2, 1
    np.testing.assert_array_equal(np.asarray(counts), expect)

    logits = jnp.zeros((2, 8), jnp.float32)
    pres = jnp.asarray([0.5, 0.0], jnp.float32)
    freq = jnp.asarray([0.25, 0.0], jnp.float32)
    pen = np.asarray(apply_penalties(logits, counts, pres, freq))
    # row 0: token 3 seen twice -> -(0.5 + 0.25*2) = -1.0; token 5 -> -1.0;
    # unseen tokens untouched. row 1: no penalties configured.
    assert pen[0, 3] == pytest.approx(-1.0)
    assert pen[0, 5] == pytest.approx(-1.0)
    assert pen[0, 0] == 0.0 and np.all(pen[1] == 0.0)


def test_row_sample_keys_seeded_deterministic():
    """Seeded rows ignore the engine step key (reproducible across engines
    and window boundaries); unseeded rows follow it."""
    seed = jnp.asarray([42, -1], jnp.int32)
    pos = jnp.asarray([7, 7], jnp.int32)
    k1 = jax.random.key_data(row_sample_keys(jax.random.key(1), seed, pos))
    k2 = jax.random.key_data(row_sample_keys(jax.random.key(2), seed, pos))
    np.testing.assert_array_equal(np.asarray(k1[0]), np.asarray(k2[0]))
    assert not np.array_equal(np.asarray(k1[1]), np.asarray(k2[1]))
    # a different position changes the seeded key (new draw per token)
    k3 = jax.random.key_data(row_sample_keys(
        jax.random.key(1), seed, jnp.asarray([8, 7], jnp.int32)))
    assert not np.array_equal(np.asarray(k1[0]), np.asarray(k3[0]))


def test_sample_and_logprobs_row_keys_seeded_rows_reproduce():
    rng = np.random.default_rng(9)
    row = rng.standard_normal((256,)).astype(np.float32)
    logits = jnp.asarray(np.stack([row, row]))   # identical distributions
    temp = jnp.ones((2,), jnp.float32)
    tk = jnp.zeros((2,), jnp.int32)
    tp = jnp.ones((2,), jnp.float32)
    seed = jnp.asarray([7, 7], jnp.int32)
    pos = jnp.asarray([3, 3], jnp.int32)
    ids_a, _ = sample_and_logprobs(
        logits, row_sample_keys(jax.random.key(0), seed, pos), temp, tk, tp,
        row_keys=True)
    ids_b, _ = sample_and_logprobs(
        logits, row_sample_keys(jax.random.key(99), seed, pos), temp, tk, tp,
        row_keys=True)
    np.testing.assert_array_equal(np.asarray(ids_a), np.asarray(ids_b))
    # identical rows with identical seeds draw the same token
    assert int(ids_a[0]) == int(ids_a[1])


def test_token_logprobs_temperature_scaling():
    """Logprobs are reported under the temperature-scaled distribution
    (vLLM's logits-processor order); greedy rows use the raw distribution."""
    rng = np.random.default_rng(6)
    B, V = 4, 256
    logits = jnp.asarray(rng.standard_normal((B, V)).astype(np.float32))
    tokens = jnp.asarray(rng.integers(0, V, B), jnp.int32)
    temp = jnp.asarray([0.0, 1.0, 2.0, 0.5], jnp.float32)
    got = token_logprobs(logits, tokens, temp)

    logp_raw = jax.nn.log_softmax(logits, axis=-1)
    logp_t2 = jax.nn.log_softmax(logits / 2.0, axis=-1)
    logp_h = jax.nn.log_softmax(logits / 0.5, axis=-1)
    np.testing.assert_allclose(got[0], logp_raw[0, tokens[0]], rtol=1e-5)
    np.testing.assert_allclose(got[1], logp_raw[1, tokens[1]], rtol=1e-5)
    np.testing.assert_allclose(got[2], logp_t2[2, tokens[2]], rtol=1e-5)
    np.testing.assert_allclose(got[3], logp_h[3, tokens[3]], rtol=1e-5)

    # Backwards-compatible default: no temperature arg => raw distribution.
    got_none = token_logprobs(logits, tokens)
    np.testing.assert_allclose(np.asarray(got_none),
                               np.asarray(logp_raw[jnp.arange(B), tokens]),
                               rtol=1e-5)
