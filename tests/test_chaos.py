"""Chaos suite: every resilience recovery path exercised under KGCT_FAULT
(deterministic fault injection, JAX_PLATFORMS=cpu, no real failures):

- admission control sheds a budget-blown request with 429 + Retry-After
  while unbudgeted requests keep flowing;
- SIGTERM drain finishes in-flight streams, rejects new work with 503, and
  flips /health before exit;
- an injected step stall trips the watchdog (health 503) and self-heals;
- a broadcast failure (dead follower) group-aborts in-flight work and the
  leader stays serveable;
- a follower whose leader dies (or goes silent) group-aborts and flips its
  liveness-tied health endpoint;
- router: connect-phase retry with backoff, stalled-stream circuit breaking
  with rebalance + recovery, bounded metrics scrapes, cold-start probing,
  and OpenAI-shaped 503s.

All tests are `chaos`-marked, seeded, and keep every sleep under 1 s.
"""

import asyncio
import dataclasses
import json
import socket
import threading
import time
import types
import urllib.error
import urllib.request

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, ResilienceConfig, SchedulerConfig,
    get_model_config)
from kubernetes_gpu_cluster_tpu.engine import SamplingParams
from kubernetes_gpu_cluster_tpu.resilience import (DrainState, LoopLiveness,
                                                   configure_faults)
from kubernetes_gpu_cluster_tpu.resilience.drain import install_sigterm_drain
from kubernetes_gpu_cluster_tpu.serving.api_server import (TTFT_BUDGET_HEADER,
                                                           build_server)
from kubernetes_gpu_cluster_tpu.serving.multihost import (DirectiveFollower,
                                                          DirectiveLeader,
                                                          serve_follower_health)
from kubernetes_gpu_cluster_tpu.serving.router import Router

from test_serving import _assert_valid_exposition

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clear_faults():
    configure_faults(None)
    yield
    configure_faults(None)


def _engine_config(**res_kw):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=256,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(128, 256),
                                  decode_window=4),
        resilience=ResilienceConfig(**res_kw))


_SRV: dict = {}


@pytest.fixture(scope="module")
def chaos_client():
    """One engine + server for the module; watchdog tight enough to catch an
    injected 0.6 s stall within the test's polling window."""
    loop = asyncio.new_event_loop()
    server = build_server(_engine_config(watchdog_timeout_s=0.1),
                          tokenizer_path=None, model_name="debug-tiny")
    _SRV["api"] = server
    client = TestClient(TestServer(server.build_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client, server
    loop.run_until_complete(client.close())
    loop.close()


async def _complete(client, timeout_budget_ms=None, **body):
    body.setdefault("prompt", "hello")
    body.setdefault("max_tokens", 4)
    body.setdefault("temperature", 0.0)
    headers = {}
    if timeout_budget_ms is not None:
        headers[TTFT_BUDGET_HEADER] = str(timeout_budget_ms)
    return await client.post("/v1/completions", json=body, headers=headers)


class TestAdmissionShedding:
    def test_shed_429_with_retry_after(self, chaos_client):
        loop, client, server = chaos_client

        async def go():
            configure_faults("queue_wait_est:value=30")
            # Budget below the (forced) 30 s estimate: shed, not queued.
            t0 = time.monotonic()
            r = await _complete(client, timeout_budget_ms=1000)
            elapsed = time.monotonic() - t0
            assert r.status == 429
            assert elapsed < 1.0, "shed must be immediate, not queued"
            assert int(r.headers["Retry-After"]) >= 30
            err = (await r.json())["error"]
            assert err["type"] == "overloaded_error" and err["code"] == 429
            # Unbudgeted traffic is untouched (default budget is None).
            r2 = await _complete(client)
            assert r2.status == 200
            # Generous budget admits through the same estimate.
            r3 = await _complete(client, timeout_budget_ms=60_000)
            assert r3.status == 200
            configure_faults(None)
            assert server.admission.shed_total >= 1
        loop.run_until_complete(go())

    def test_invalid_budget_header_400(self, chaos_client):
        loop, client, _ = chaos_client

        async def go():
            r = await _complete(client, timeout_budget_ms="soon")
            assert r.status == 400
            r = await _complete(client, timeout_budget_ms=-5)
            assert r.status == 400
        loop.run_until_complete(go())

    def test_shed_counter_in_metrics(self, chaos_client):
        loop, client, _ = chaos_client

        async def go():
            r = await client.get("/metrics")
            text = await r.text()
            _assert_valid_exposition(text)
            shed = [l for l in text.splitlines()
                    if l.startswith("kgct_requests_shed_total")]
            assert shed and int(shed[0].split()[-1]) >= 1
            assert "kgct_watchdog_trips_total" in text
            assert "kgct_drain_state 0" in text
        loop.run_until_complete(go())


class TestWatchdog:
    def test_injected_stall_trips_health_then_recovers(self, chaos_client):
        loop, client, server = chaos_client

        async def go():
            configure_faults("step_stall:delay=0.6,times=1")
            task = asyncio.get_event_loop().create_task(
                _complete(client, max_tokens=2))
            # During the stalled step the watchdog (timeout 0.1 s) must flip
            # /health to 503.
            saw_503 = False
            for _ in range(40):
                r = await client.get("/health")
                if r.status == 503:
                    body = await r.json()
                    assert "watchdog" in body["status"]
                    saw_503 = True
                    break
                await asyncio.sleep(0.02)
            assert saw_503, "watchdog never tripped during injected stall"
            assert server.watchdog.trips >= 1
            # The stall ends; the request completes and health self-heals.
            r = await task
            assert r.status == 200
            for _ in range(40):
                r = await client.get("/health")
                if r.status == 200:
                    return
                await asyncio.sleep(0.02)
            raise AssertionError("health did not recover after stall ended")
        loop.run_until_complete(go())

    def test_watchdog_trip_dumps_flight_recorder(self, chaos_client,
                                                 monkeypatch, tmp_path):
        """A watchdog trip auto-dumps the black-box flight recorder: the
        file holds the triggering event plus the ring of events/snapshots
        that preceded the hang (the ISSUE's crash-capture contract)."""
        loop, client, server = chaos_client
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def go():
            configure_faults("step_stall:delay=0.6,times=1")
            task = asyncio.get_event_loop().create_task(
                _complete(client, max_tokens=2))
            dump = None
            for _ in range(80):
                dumps = sorted(tmp_path.glob("flight-watchdog_trip-*.json"))
                if dumps:
                    dump = dumps[0]
                    break
                await asyncio.sleep(0.025)
            r = await task
            assert r.status == 200
            assert dump is not None, "watchdog trip produced no dump"
            doc = json.loads(dump.read_text())
            assert doc["reason"] == "watchdog_trip"
            kinds = [e["kind"] for e in doc["events"]]
            assert "watchdog_trip" in kinds          # the trigger itself
            # The preceding seconds: lifecycle events and at least one
            # periodic state snapshot (queue depths / KV occupancy) from
            # the module's earlier traffic.
            assert "snapshot" in kinds
            snap = next(e for e in doc["events"] if e["kind"] == "snapshot")
            assert {"waiting", "running", "kv_pages_free"} <= set(snap)
            # Health recovers (the stall was transient).
            for _ in range(40):
                if (await client.get("/health")).status == 200:
                    return
                await asyncio.sleep(0.02)
            raise AssertionError("health did not recover after stall ended")
        loop.run_until_complete(go())


class TestGracefulDrain:
    def test_drain_finishes_inflight_and_rejects_new(self, chaos_client,
                                                     monkeypatch, tmp_path):
        loop, client, server = chaos_client
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def go():
            r = await client.post("/v1/completions", json={
                "prompt": "drain me", "max_tokens": 24, "temperature": 0.0,
                "stream": True})
            assert r.status == 200
            it = r.content.__aiter__()
            await it.__anext__()               # stream demonstrably started
            drained = []
            task = server.begin_drain(on_drained=lambda: drained.append(1))
            assert task is not None
            assert server.begin_drain() is None     # idempotent
            # Drain start auto-dumped the flight recorder (what was queued
            # or mid-stream when the SIGTERM landed outlives the pod).
            [dump] = sorted(tmp_path.glob("flight-sigterm_drain-*.json"))
            assert json.loads(dump.read_text())["reason"] == "sigterm_drain"
            # New admissions are rejected with the OpenAI envelope...
            r2 = await _complete(client)
            assert r2.status == 503
            err = (await r2.json())["error"]
            assert err["type"] == "overloaded_error"
            assert "Retry-After" in r2.headers
            # ...and /health flips so k8s takes the pod out of rotation.
            rh = await client.get("/health")
            assert rh.status == 503
            # The in-flight stream keeps going to [DONE].
            saw_done = False
            async for line in r.content:
                if line.decode().strip() == "data: [DONE]":
                    saw_done = True
            assert saw_done, "drain truncated an in-flight stream"
            await asyncio.wait_for(task, timeout=5)
            assert drained == [1]
            assert server.drain_state.gauge_value == 2
            rm = await client.get("/metrics")
            assert "kgct_drain_state 2" in await rm.text()
        loop.run_until_complete(go())
        # Reset for any later use of the module server: a real pod exits
        # after drain; the test server lives on.
        server.drain_state = DrainState()
        server.hub.drain = server.drain_state

    def test_migrate_fail_degrades_to_wait_it_out(self, chaos_client):
        """``migrate_fail`` chaos: the live-migration export raises before
        the sequence detaches, so THAT stream keeps decoding locally (the
        pre-migration wait-it-out drain) and still reaches [DONE] — with
        the fallback attributed in the migration series and a trace span."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            MIGRATE_URL_HEADER)
        loop, client, server = chaos_client

        async def go():
            configure_faults("migrate_fail")
            r = await client.post("/v1/completions", json={
                "prompt": "migrate me", "max_tokens": 16,
                "temperature": 0.0, "stream": True},
                headers={MIGRATE_URL_HEADER: "http://127.0.0.1:1"})
            assert r.status == 200
            it = r.content.__aiter__()
            await it.__anext__()               # stream demonstrably started
            task = server.begin_drain()
            assert task is not None
            saw_done, saw_error = False, False
            async for line in r.content:
                text = line.decode().strip()
                if text == "data: [DONE]":
                    saw_done = True
                elif text.startswith("data:") and '"error"' in text:
                    saw_error = True
            assert saw_done and not saw_error, \
                "migrate_fail must degrade to wait-it-out, not truncate"
            await asyncio.wait_for(task, timeout=10)
            assert server.migration.migrations.get(
                ("push", "fallback"), 0) >= 1
            assert server.migration.migrations.get(("push", "ok"), 0) == 0
            events = server.engine.engine.obs.flight.export()["events"]
            assert any(e["kind"] == "migrate"
                       and e.get("outcome") == "fallback" for e in events)
            rm = await client.get("/metrics")
            text = await rm.text()
            assert 'kgct_migrations_total{side="push",outcome="fallback"}' \
                in text
        loop.run_until_complete(go())
        server.drain_state = DrainState()
        server.hub.drain = server.drain_state

    def test_push_failure_reimports_locally(self, chaos_client):
        """Rung 2 of the push ladder: the export succeeded (the sequence
        detached) but the peer is unreachable — the snapshot re-imports
        LOCALLY and the stream resumes here as if never exported,
        byte-identical to an undrained run."""
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            MIGRATE_URL_HEADER)
        loop, client, server = chaos_client
        body = {"prompt": "push me somewhere", "max_tokens": 16,
                "temperature": 0.0}

        async def go():
            r = await client.post("/v1/completions", json=body)
            assert r.status == 200
            ref = (await r.json())["choices"][0]["text"]
            r = await client.post(
                "/v1/completions", json=dict(body, stream=True),
                headers={MIGRATE_URL_HEADER: "http://127.0.0.1:1"})
            assert r.status == 200
            chunks = []
            it = r.content.__aiter__()
            chunks.append(await it.__anext__())
            task = server.begin_drain()
            assert task is not None
            async for line in r.content:
                chunks.append(line)
            await asyncio.wait_for(task, timeout=10)
            text, saw_done = [], False
            for line in chunks:
                s = line.decode().strip()
                if s == "data: [DONE]":
                    saw_done = True
                elif s.startswith("data:"):
                    obj = json.loads(s[5:].strip())
                    assert "error" not in obj, obj
                    text.append(obj["choices"][0]["text"])
            assert saw_done
            assert "".join(text) == ref, \
                "local re-import must resume byte-identically"
            assert server.migration.migrations.get(
                ("push", "fallback"), 0) >= 1
        loop.run_until_complete(go())
        server.drain_state = DrainState()
        server.hub.drain = server.drain_state

    def test_sigterm_handler_drives_drain(self):
        import os
        import signal

        class _Eng:
            def has_unfinished_requests(self):
                return False

        shim = types.SimpleNamespace(engine=_Eng())

        async def scenario():
            loop = asyncio.get_running_loop()
            drain = DrainState()
            fired = []
            uninstall = install_sigterm_drain(
                loop, drain, shim, grace_s=1.0,
                on_drained=lambda: fired.append(1))
            try:
                os.kill(os.getpid(), signal.SIGTERM)
                deadline = time.monotonic() + 2.0
                while drain.gauge_value != 2 and time.monotonic() < deadline:
                    await asyncio.sleep(0.01)
                assert drain.gauge_value == 2 and fired == [1]
                # Repeat SIGTERM during/after drain is harmless.
                os.kill(os.getpid(), signal.SIGTERM)
                await asyncio.sleep(0.02)
            finally:
                uninstall()

        asyncio.run(scenario())


class TestResumeAndRecv:
    """The session-survivability server seams on the warm module server:
    /internal/resume reconstructs a relayed stream by token replay
    (byte-identical continuation, only new tokens emitted), and the
    migration-push receive direction of /internal/kv_handoff validates
    before parking."""

    def test_resume_token_replay_emits_only_new_tokens(self, chaos_client):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            MIGRATE_URL_HEADER, REQUEST_ID_HEADER, RESUME_MODE_HEADER)
        loop, client, server = chaos_client
        body = {"prompt": "resume this stream", "max_tokens": 12,
                "temperature": 0.0}

        async def go():
            # A migration-registered stream embeds its per-frame token
            # ledger (what the router keeps, and what a failover replays).
            r = await client.post(
                "/v1/completions", json=dict(body, stream=True),
                headers={MIGRATE_URL_HEADER: "http://127.0.0.1:1"})
            assert r.status == 200
            frames = []
            async for line in r.content:
                s = line.decode().strip()
                if s.startswith("data:") and s != "data: [DONE]":
                    frames.append(json.loads(s[5:].strip()))
            toks = [t for f in frames for t in f.get("kgct_token_ids", [])]
            full = "".join(f["choices"][0]["text"] for f in frames)
            assert len(toks) == 12, "ledger must cover every token"
            # Replay the first 5 tokens' worth: the resumed stream must
            # carry ONLY the remainder, byte-identical.
            cut, prefix = 0, ""
            for f in frames:
                if cut >= 5:
                    break
                cut += len(f.get("kgct_token_ids", []))
                prefix += f["choices"][0]["text"]
            resume = await client.post(
                "/internal/resume",
                json={"body": body, "kind": "completion",
                      "relayed_token_ids": toks[:cut]},
                headers={REQUEST_ID_HEADER: "resume-replay-1"})
            assert resume.status == 200, await resume.text()
            assert resume.headers[RESUME_MODE_HEADER] == "recompute"
            got, saw_done = [], False
            async for line in resume.content:
                s = line.decode().strip()
                if s == "data: [DONE]":
                    saw_done = True
                elif s.startswith("data:"):
                    obj = json.loads(s[5:].strip())
                    assert "error" not in obj, obj
                    got.append(obj["choices"][0]["text"])
            assert saw_done
            assert "".join(got) == full[len(prefix):]
            assert server.migration.migrations.get(
                ("resume", "fallback"), 0) >= 1
            events = server.engine.engine.obs.flight.export()["events"]
            assert any(e["kind"] == "migrate"
                       and e.get("side") == "resume" for e in events)
        loop.run_until_complete(go())

    def test_resume_rejects_malformed_envelopes(self, chaos_client):
        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)
        loop, client, _ = chaos_client

        async def go():
            hdr = {REQUEST_ID_HEADER: "resume-bad-1"}
            r = await client.post("/internal/resume", data=b"not json",
                                  headers=hdr)
            assert r.status == 400
            r = await client.post("/internal/resume", json={
                "body": "nope", "relayed_token_ids": []}, headers=hdr)
            assert r.status == 400
            r = await client.post("/internal/resume", json={
                "body": {"prompt": "x"},
                "relayed_token_ids": [1, "two"]}, headers=hdr)
            assert r.status == 400
            r = await client.post("/internal/resume", json={
                "body": {"prompt": "x"}, "relayed_token_ids": [],
                "kind": "mystery"}, headers=hdr)
            assert r.status == 400
        loop.run_until_complete(go())

    def test_recv_validates_before_parking(self, chaos_client):
        import numpy as np

        from kubernetes_gpu_cluster_tpu.serving.errors import (
            REQUEST_ID_HEADER)
        from kubernetes_gpu_cluster_tpu.serving.handoff import encode_handoff
        loop, client, server = chaos_client

        def blob(model="debug-tiny", mid_stream=True):
            k = np.zeros((1, 2, 4, 4), dtype="float32")
            state = {"model": model, "page_size": 16, "dtype": "float32",
                     "prompt_token_ids": [1, 2, 3],
                     "output_token_ids": [7], "output_logprobs": [-0.5],
                     "output_top_logprobs": [], "k": k, "v": k}
            if mid_stream:
                state["mid_stream"] = True
            # Speak the current wire dialect: the receiver requires the
            # integrity extension by default (a plain frame is a 426
            # skew rejection before any semantic validation).
            return encode_handoff(state, integrity=True)

        async def go():
            octet = {"Content-Type": "application/octet-stream",
                     REQUEST_ID_HEADER: "park-1"}
            errs0 = server.migration.migrations.get(("recv", "error"), 0)
            # Model mismatch: 409, never parked.
            r = await client.post("/internal/kv_handoff",
                                  data=blob(model="llama-3-8b"),
                                  headers=octet)
            assert r.status == 409
            # A held-prefill export is NOT a mid-stream state: 400.
            r = await client.post("/internal/kv_handoff",
                                  data=blob(mid_stream=False),
                                  headers=octet)
            assert r.status == 400
            # Garbage frame: 400.
            r = await client.post("/internal/kv_handoff", data=b"KVGARBAGE",
                                  headers=octet)
            assert r.status == 400
            assert server.migration.migrations.get(
                ("recv", "error"), 0) == errs0 + 3
            assert len(server.migrate_store) == 0
            # A well-formed push parks (and is claimable exactly once).
            r = await client.post("/internal/kv_handoff", data=blob(),
                                  headers=octet)
            assert r.status == 200
            assert (await r.json())["parked"] is True
            assert server.migrate_store.pop("park-1") is not None
            assert server.migrate_store.pop("park-1") is None
        loop.run_until_complete(go())


@pytest.fixture(scope="module")
def leader_client():
    """API server whose engine broadcasts step directives to a fake follower
    (a TCP sink) — the multihost leader path without a second engine."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    stop = threading.Event()

    def _sink():
        srv.settimeout(10)
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        conn.settimeout(0.1)
        with conn:
            while not stop.is_set():
                try:
                    if not conn.recv(1 << 16):
                        return
                except socket.timeout:
                    continue
                except OSError:
                    return

    t = threading.Thread(target=_sink, daemon=True)
    t.start()
    leader = DirectiveLeader([f"127.0.0.1:{port}"],
                             heartbeat_interval_s=0)
    loop = asyncio.new_event_loop()
    server = build_server(_engine_config(), tokenizer_path=None,
                          model_name="debug-tiny", leader=leader)
    client = TestClient(TestServer(server.build_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client, server
    stop.set()
    loop.run_until_complete(client.close())
    loop.close()
    srv.close()


class TestMultihostLeader:
    def test_broadcast_fail_group_aborts_and_leader_stays_serveable(
            self, leader_client, monkeypatch, tmp_path):
        loop, client, server = leader_client
        monkeypatch.setenv("KGCT_FLIGHT_DIR", str(tmp_path))

        async def go():
            # Healthy lockstep first: broadcasts reach the fake follower.
            r = await _complete(client)
            assert r.status == 200
            assert server.engine.leader is not None
            # Kill the "rank": the 3rd broadcast of the next request (add,
            # then steps) raises — mid-generation, with work in flight.
            configure_faults("broadcast_fail:after=2,times=1")
            r2 = await _complete(client, max_tokens=32)
            assert r2.status >= 500     # in-flight waiter failed loudly
            # Group-abort left no orphaned device work behind...
            eng = server.engine.engine
            deadline = time.monotonic() + 5
            while eng.has_unfinished_requests() and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert not eng.has_unfinished_requests()
            # ...the broken process group is detached, and rank 0 serves on.
            assert server.engine.leader is None
            r3 = await _complete(client)
            assert r3.status == 200
            # The fatal group-abort auto-dumped the flight recorder with
            # the triggering event and the in-flight work it found.
            [dump] = sorted(tmp_path.glob("flight-group_abort-*.json"))
            doc = json.loads(dump.read_text())
            assert doc["reason"] == "group_abort"
            trigger = [e for e in doc["events"]
                       if e["kind"] == "group_abort"]
            assert trigger and trigger[-1]["requests"] >= 1
            # The ring captured the seconds before: the doomed request's
            # lifecycle events are in the dump.
            assert any(e["kind"] == "arrival" for e in doc["events"])
        loop.run_until_complete(go())


class _RecordingEngine:
    """Duck-typed LLMEngine for follower-side protocol tests (no jax)."""

    def __init__(self):
        self.added, self.aborted = [], []
        self.steps = 0
        self.scheduler = types.SimpleNamespace(waiting=[], running=[])

    def add_request(self, rid, ids, params):
        self.added.append(rid)
        self.scheduler.running.append(
            types.SimpleNamespace(request_id=rid))

    def abort_request(self, rid):
        self.aborted.append(rid)
        self.scheduler.running = [
            s for s in self.scheduler.running if s.request_id != rid]
        return True

    def has_unfinished_requests(self):
        return bool(self.scheduler.running or self.scheduler.waiting)

    def step(self):
        self.steps += 1
        return []


def _directive(adds=(), aborts=()):
    payload = {"adds": [[rid, ids, dataclasses.asdict(params)]
                        for rid, ids, params in adds],
               "aborts": list(aborts)}
    return (json.dumps(payload) + "\n").encode()


class TestMultihostFollower:
    def test_leader_close_group_aborts_and_health_flips(self):
        follower = DirectiveFollower(port=0, host="127.0.0.1")
        engine = _RecordingEngine()
        liveness = LoopLiveness(timeout_s=30)
        health = serve_follower_health(0, host="127.0.0.1",
                                       liveness=liveness)
        hport = health.server_address[1]

        def _health_status():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{hport}/health", timeout=2) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        t = threading.Thread(
            target=follower.run,
            kwargs=dict(engine=engine, liveness=liveness,
                        liveness_timeout_s=5.0),
            daemon=True)
        t.start()
        conn = socket.create_connection(("127.0.0.1", follower.port),
                                        timeout=2)
        conn.sendall(_directive(
            adds=[("r1", [1, 2, 3], SamplingParams(max_tokens=4))]))
        deadline = time.monotonic() + 2
        while "r1" not in engine.added and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.added == ["r1"] and engine.steps == 1
        assert _health_status() == 200
        # Leader dies mid-flight: the follower group-aborts r1, exits its
        # loop, and its health endpoint goes 503 for kubelet to restart it.
        conn.close()
        t.join(timeout=2)
        assert not t.is_alive()
        assert engine.aborted == ["r1"]
        assert not engine.has_unfinished_requests()
        assert _health_status() == 503
        health.shutdown()

    def test_leader_silence_past_liveness_timeout_aborts(self):
        follower = DirectiveFollower(port=0, host="127.0.0.1")
        engine = _RecordingEngine()
        liveness = LoopLiveness(timeout_s=30)
        t = threading.Thread(
            target=follower.run,
            kwargs=dict(engine=engine, liveness=liveness,
                        liveness_timeout_s=0.2),
            daemon=True)
        t.start()
        conn = socket.create_connection(("127.0.0.1", follower.port),
                                        timeout=2)
        conn.sendall(_directive(
            adds=[("r1", [1], SamplingParams(max_tokens=4))]))
        # Keep the socket open but silent: no directives, no heartbeats.
        t.join(timeout=2)
        assert not t.is_alive(), "follower must declare a silent leader dead"
        assert engine.aborted == ["r1"]
        assert not liveness.alive()
        conn.close()

    def test_heartbeats_keep_idle_follower_alive(self):
        follower = DirectiveFollower(port=0, host="127.0.0.1")
        engine = _RecordingEngine()
        liveness = LoopLiveness(timeout_s=30)
        t = threading.Thread(
            target=follower.run,
            kwargs=dict(engine=engine, liveness=liveness,
                        liveness_timeout_s=0.3),
            daemon=True)
        t.start()
        leader = DirectiveLeader([f"127.0.0.1:{follower.port}"],
                                 heartbeat_interval_s=0.05)
        # First broadcast connects and starts the heartbeat thread.
        leader.broadcast([], [])
        # Idle for > liveness timeout: only heartbeats flow, and they are
        # enough — the follower must NOT declare the leader dead.
        time.sleep(0.6)
        assert t.is_alive()
        assert liveness.alive()
        assert engine.aborted == []
        leader.close()                    # stop directive: clean exit
        t.join(timeout=2)
        assert not t.is_alive()
        assert engine.aborted == []


class TestKVSwapChaos:
    def test_swap_out_failure_degrades_to_recompute_never_wedges(self):
        """KGCT_FAULT=kv_swap_fail: every swap-out raises inside the
        swapper. The scheduler must degrade each preemption to recompute —
        the victim re-prefills and finishes, nothing wedges, no sequence is
        stranded on the swapped queue, and no host page leaks."""
        from kubernetes_gpu_cluster_tpu.engine import LLMEngine

        cfg = EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=8, num_pages=8, swap_space_gb=0.05),
            scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=256,
                                      decode_buckets=(1, 2, 4),
                                      prefill_buckets=(32, 64),
                                      decode_window=4))
        eng = LLMEngine(cfg)
        assert eng.swapper is not None
        configure_faults("kv_swap_fail")
        outs = eng.generate(
            [[9, 8, 7, 6], [1, 2, 3, 4], [5, 5, 5, 5]],
            SamplingParams(max_tokens=16, temperature=0.0))
        configure_faults(None)
        assert [o.finished for o in outs] == [True] * 3
        assert all(len(o.output_token_ids) == 16 for o in outs)
        kinds = eng.scheduler.num_preemptions_by_kind
        assert kinds["recompute"] > 0, "pressure never preempted"
        assert kinds["swap"] == 0, "a failed swap-out was counted as a swap"
        assert not eng.scheduler.swapped
        assert eng.swapper.host.num_in_use == 0
        assert not eng.has_unfinished_requests()


# --------------------------------------------------------------------------
# Router chaos
# --------------------------------------------------------------------------

async def _mini_replica(response_delay_s=0.0, metrics_delay_s=0.0,
                        stream_stall_s=0.0):
    """A stand-in engine replica: /health, /metrics, /v1/completions.
    ``response_delay_s`` delays the response headers (wedged pre-response);
    ``stream_stall_s`` sends one chunk then goes silent (mid-stream hang)."""
    from aiohttp import web as aioweb

    async def health(request):
        return aioweb.json_response({"status": "ok"})

    async def metrics(request):
        if metrics_delay_s:
            await asyncio.sleep(metrics_delay_s)
        return aioweb.Response(
            text="# TYPE kgct_requests_total counter\nkgct_requests_total 1\n",
            content_type="text/plain")

    async def completions(request):
        if response_delay_s:
            await asyncio.sleep(response_delay_s)
        if stream_stall_s:
            resp = aioweb.StreamResponse()
            await resp.prepare(request)
            await resp.write(b"data: first\n\n")
            await asyncio.sleep(stream_stall_s)   # then silence
            return resp
        return aioweb.json_response({"object": "completion", "ok": True})

    app = aioweb.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/v1/completions", completions)
    runner = aioweb.AppRunner(app)
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, f"http://127.0.0.1:{runner.addresses[0][1]}"


async def _start_router(router):
    client = TestClient(TestServer(router.build_app()))
    await client.start_server()
    return client


class TestRouterChaos:
    def test_connect_fault_retried_with_backoff(self):
        async def scenario():
            runner, url = await _mini_replica()
            router = Router([url], health_interval_s=9999,
                            connect_retries=2, retry_backoff_s=0.01)
            client = await _start_router(router)
            try:
                configure_faults("router_connect:times=1")
                r = await client.post("/v1/completions", json={"prompt": "x"})
                # The injected connect failure is retried (bounded backoff)
                # and the request still succeeds.
                assert r.status == 200
                assert (await r.json())["ok"] is True
                assert router.retries_total >= 1
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())

    def test_injected_hang_circuit_breaks_then_recovers(self):
        async def scenario():
            runner, url = await _mini_replica()
            router = Router([url], health_interval_s=9999, fail_threshold=1)
            client = await _start_router(router)
            try:
                configure_faults("replica_hang:times=1")
                r = await client.post("/v1/completions", json={"prompt": "x"})
                # Stream terminated mid-flight (truncation is the signal)
                # and the replica is circuit-broken.
                assert not router.replicas[0].healthy
                # With no healthy replica: OpenAI-shaped 503 + Retry-After.
                r2 = await client.post("/v1/completions",
                                       json={"prompt": "x"})
                assert r2.status == 503
                err = (await r2.json())["error"]
                assert err["type"] == "overloaded_error"
                assert int(r2.headers["Retry-After"]) >= 1
                # A 200 probe alone must NOT lift a traffic bench during
                # the cooldown (the wedge outlives one good /health)...
                assert router.replicas[0].benched_until > time.monotonic()
                await router._check(router.replicas[0])
                assert not router.replicas[0].healthy
                # ...after the cooldown lapses, the probe restores it and
                # traffic flows again.
                router.replicas[0].benched_until = 0.0
                await router._check(router.replicas[0])
                assert router.replicas[0].healthy
                r3 = await client.post("/v1/completions",
                                       json={"prompt": "x"})
                assert r3.status == 200
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())

    def test_wedged_replica_no_response_rebalances(self):
        async def scenario():
            stall_runner, stall_url = await _mini_replica(
                response_delay_s=30.0)
            live_runner, live_url = await _mini_replica()
            router = Router([stall_url, live_url], health_interval_s=9999,
                            fail_threshold=1, response_timeout_s=0.3)
            client = await _start_router(router)
            try:
                # First request lands on the wedged replica (rr tie-break
                # picks index 0), exceeds the headers deadline, and circuit-
                # breaks it; the request was already sent so it is NOT
                # replayed (502, not silent double work).
                r = await client.post("/v1/completions", json={"prompt": "x"})
                assert r.status == 502
                assert not router.replicas[0].healthy
                # Traffic rebalances to the healthy peer.
                for _ in range(3):
                    r = await client.post("/v1/completions",
                                          json={"prompt": "x"})
                    assert r.status == 200
            finally:
                await client.close()
                await stall_runner.cleanup()
                await live_runner.cleanup()
        asyncio.run(scenario())

    def test_midstream_stall_circuit_breaks_and_rebalances(self):
        async def scenario():
            stall_runner, stall_url = await _mini_replica(
                stream_stall_s=30.0)
            live_runner, live_url = await _mini_replica()
            router = Router([stall_url, live_url], health_interval_s=9999,
                            fail_threshold=1, stall_timeout_s=0.3)
            client = await _start_router(router)
            try:
                # One chunk arrives, then silence past stall_timeout_s: the
                # committed client stream is terminated (truncation is the
                # signal) and the replica circuit-broken.
                r = await client.post("/v1/completions", json={"prompt": "x"})
                body = await r.read()
                assert b"first" in body          # stream had started
                assert not router.replicas[0].healthy
                # Traffic rebalances to the healthy peer.
                r2 = await client.post("/v1/completions",
                                       json={"prompt": "x"})
                assert r2.status == 200
                assert (await r2.json())["ok"] is True
            finally:
                await client.close()
                await stall_runner.cleanup()
                await live_runner.cleanup()
        asyncio.run(scenario())

    def test_retry_rounds_reach_benched_replica(self):
        """fail_threshold=1 benches the replica on its first injected
        connect failure — the retry round must still probe it (nothing was
        sent, so a desperation probe is safe) instead of giving up."""
        async def scenario():
            runner, url = await _mini_replica()
            router = Router([url], health_interval_s=9999, fail_threshold=1,
                            connect_retries=2, retry_backoff_s=0.01)
            client = await _start_router(router)
            try:
                configure_faults("router_connect:times=1")
                r = await client.post("/v1/completions", json={"prompt": "x"})
                assert r.status == 200      # retried despite being benched
                assert router.retries_total >= 1
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())

    def test_metrics_scrape_skips_stragglers(self):
        async def scenario():
            slow_runner, slow_url = await _mini_replica(metrics_delay_s=30.0)
            fast_runner, fast_url = await _mini_replica()
            router = Router([slow_url, fast_url], health_interval_s=9999,
                            metrics_timeout_s=0.2)
            client = await _start_router(router)
            try:
                t0 = time.monotonic()
                r = await client.get("/metrics")
                assert time.monotonic() - t0 < 2.0, \
                    "one stalled replica must not hang the scrape"
                text = await r.text()
                _assert_valid_exposition(text)
                # The fast replica's series made it, relabelled; the
                # straggler's engine series did not (its router-level health
                # gauges legitimately remain).
                assert f'kgct_requests_total{{replica="{fast_url}"' in text
                assert not any(
                    line.startswith("kgct_requests_total") and slow_url in line
                    for line in text.splitlines())
                errs = [l for l in text.splitlines() if l.startswith(
                    "kgct_router_metrics_scrape_errors_total")]
                assert errs and int(errs[0].split()[-1]) == 1
            finally:
                await client.close()
                await slow_runner.cleanup()
                await fast_runner.cleanup()
        asyncio.run(scenario())

    def test_cold_start_probe_removes_dead_replica_immediately(self):
        async def scenario():
            runner, url = await _mini_replica()
            dead = "http://127.0.0.1:1"
            router = Router([dead, url], health_interval_s=9999)
            client = await _start_router(router)
            try:
                # No interval wait: startup already probed both.
                assert router.replicas[0].healthy is False
                assert router.replicas[1].healthy is True
                r = await client.post("/v1/completions", json={"prompt": "x"})
                assert r.status == 200
            finally:
                await client.close()
                await runner.cleanup()
        asyncio.run(scenario())
