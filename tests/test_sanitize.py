"""Runtime sanitizers (KGCT_SANITIZE=1) under the KGCT_FAULT chaos harness.

The acceptance bars, in order:

1. NO-OP WHEN OFF: with KGCT_SANITIZE unset the engine holds no sanitizer
   and outputs are byte-identical to a sanitized run (the guard observes,
   never perturbs).
2. A seeded NaN fault (``nan_step_output``) in the step fetch path raises
   SanitizerError at the step that produced it.
3. A seeded committed-slot KV write (``kv_commit_stomp``) — a REAL
   corruption of a spec-verify slot_mapping — is refused pre-dispatch by
   the KV shadow.
4. The shadow's stale-slot machine enforces the rollback contract
   (rejected-draft slots overwritten before any read) — unit-level, since
   a correct engine never produces the violation.
"""

import numpy as np
import pytest

import jax

from kubernetes_gpu_cluster_tpu.analysis.sanitize import (SanitizerError,
                                                          StepSanitizer,
                                                          build_step_sanitizer)
from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.spec import DraftProposer
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.resilience import configure_faults

pytestmark = pytest.mark.chaos

_MODEL = get_model_config("debug-tiny")
_PARAMS = model_lib.init_params(_MODEL, jax.random.key(7))

REPETITIVE = [7, 3, 9, 11] * 8   # n-gram structure -> spec steps engage


@pytest.fixture(autouse=True)
def _clear_faults():
    configure_faults(None)
    yield
    configure_faults(None)


class _AlwaysDraft(DraftProposer):
    """Drafts a constant token every step: guarantees spec steps engage
    (and, rejecting almost always, guarantees real rollbacks for the KV
    shadow to watch) independent of what the random-weight model emits."""

    def __init__(self, k, token=1):
        super().__init__(k)
        self.token = token

    def propose(self, token_ids):
        return [self.token] * self.k


def make_engine(spec: bool = False):
    cfg = EngineConfig(
        model=_MODEL,
        cache=CacheConfig(page_size=8, num_pages=128),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=256,
            decode_buckets=(1, 2, 4), prefill_buckets=(32, 64, 128, 256),
            decode_window=8,
            spec_decode_enabled=spec, num_speculative_tokens=4))
    engine = LLMEngine(cfg, params=_PARAMS)
    if spec:
        engine.scheduler.spec_proposer = _AlwaysDraft(4)
    return engine


class TestNoOpWhenOff:
    def test_outputs_byte_identical_with_and_without_sanitizer(
            self, monkeypatch):
        monkeypatch.delenv("KGCT_SANITIZE", raising=False)
        off = make_engine()
        assert off._sanitizer is None
        base = off.generate([REPETITIVE],
                            SamplingParams(max_tokens=12, temperature=0.0))
        monkeypatch.setenv("KGCT_SANITIZE", "1")
        on = make_engine()
        assert on._sanitizer is not None
        sane = on.generate([REPETITIVE],
                           SamplingParams(max_tokens=12, temperature=0.0))
        assert base[0].output_token_ids == sane[0].output_token_ids
        # the hooks actually ran (not vacuously clean)
        assert on._sanitizer.checks > 0

    def test_build_seam_reads_env(self, monkeypatch):
        monkeypatch.delenv("KGCT_SANITIZE", raising=False)
        assert build_step_sanitizer(8) is None
        monkeypatch.setenv("KGCT_SANITIZE", "0")
        assert build_step_sanitizer(8) is None
        monkeypatch.setenv("KGCT_SANITIZE", "1")
        assert isinstance(build_step_sanitizer(8), StepSanitizer)


class TestSeededFaults:
    def test_nan_step_output_caught(self, monkeypatch):
        monkeypatch.setenv("KGCT_SANITIZE", "1")
        engine = make_engine()
        configure_faults("nan_step_output:times=1")
        with pytest.raises(SanitizerError, match="non-finite logprob"):
            engine.generate([REPETITIVE],
                            SamplingParams(max_tokens=8, temperature=0.0))

    def test_spec_rollbacks_clean_then_seeded_stomp_caught(self, monkeypatch):
        """One spec engine, both sides of the contract. First a clean run:
        spec decode's REAL rollbacks (garbage drafts reject constantly)
        must not trip the shadow — rejected slots are overwritten before
        any read, which is exactly what it watches. Then the seeded
        committed-slot KV write (a genuine slot_mapping corruption — with
        the sanitizer off it would poison served context silently) is
        refused before the upload."""
        monkeypatch.setenv("KGCT_SANITIZE", "1")
        engine = make_engine(spec=True)
        out = engine.generate([REPETITIVE],
                              SamplingParams(max_tokens=12, temperature=0.0))
        assert engine.obs.step_kind_counts["spec"] > 0
        assert len(out[0].output_token_ids) == 12
        assert engine._sanitizer.checks > 0
        # Recycled request id (generate() numbers from zero per call): the
        # previous request's rollbacks left stale shadow entries under
        # "req-0"; a fresh sequence wearing the same id must not inherit
        # them and false-positive on a healthy engine.
        out2 = engine.generate([list(REPETITIVE) + [7, 3]],
                               SamplingParams(max_tokens=8, temperature=0.0))
        assert len(out2[0].output_token_ids) == 8
        configure_faults("kv_commit_stomp:times=1")
        with pytest.raises(SanitizerError, match="COMMITTED slot"):
            engine.generate([REPETITIVE],
                            SamplingParams(max_tokens=12, temperature=0.0))


class _FakeSeq:
    def __init__(self, rid, num_tokens, pages, finished=False):
        self.request_id = rid
        self.num_tokens = num_tokens
        self.pages = pages
        self.is_finished = finished


class _FakeSpecBatch:
    def __init__(self, seqs, seg_ids, positions, slot_mapping):
        self.seqs = seqs
        self.seg_ids = np.asarray(seg_ids, np.int32)
        self.positions = np.asarray(positions, np.int32)
        self.slot_mapping = np.asarray(slot_mapping, np.int32)


class TestKVShadowUnit:
    """The stale-slot machine, driven directly (a correct engine never
    produces these traces)."""

    PS = 8

    def _spec_step(self, san, seq, k=2):
        # writes positions n-1 .. n-1+k with matching slots
        n = seq.num_tokens
        poss = [n - 1 + i for i in range(k + 1)]
        slots = [seq.pages[p // self.PS] * self.PS + p % self.PS
                 for p in poss]
        batch = _FakeSpecBatch([seq], [0] * (k + 1), poss, slots)
        san.on_spec_dispatch(batch)
        return batch

    def test_rejected_slots_go_stale_and_overwrite_clears(self):
        san = StepSanitizer(self.PS)
        seq = _FakeSeq("r1", num_tokens=9, pages=[3, 4])   # committed KV: 8
        batch = self._spec_step(san, seq, k=2)     # writes pos 8, 9, 10
        san.on_spec_commit(batch, np.asarray([1]))  # emit 1 -> 9, 10 stale
        assert set(san._stale["r1"]) == {9, 10}
        # next decode window starts at the first stale position: clears it
        seq.num_tokens = 10
        san.on_decode_dispatch([seq], np.asarray([9]), window=8)
        assert san._stale["r1"] == {}

    def test_stale_read_detected(self):
        san = StepSanitizer(self.PS)
        seq = _FakeSeq("r1", num_tokens=9, pages=[3, 4])
        batch = self._spec_step(san, seq, k=2)
        san.on_spec_commit(batch, np.asarray([1]))  # 9, 10 stale
        # BUG trace: committed length advances past the stale slots with
        # no overwrite — the next window would read garbage as context.
        seq.num_tokens = 13
        with pytest.raises(SanitizerError, match="stale"):
            san.on_decode_dispatch([seq], np.asarray([12]), window=8)

    def test_decode_window_inside_committed_history_detected(self):
        san = StepSanitizer(self.PS)
        seq = _FakeSeq("r1", num_tokens=9, pages=[3, 4])
        with pytest.raises(SanitizerError, match="committed history"):
            san.on_decode_dispatch([seq], np.asarray([3]), window=8)

    def test_cross_sequence_committed_stomp_detected(self):
        """A slot mis-aimed into ANOTHER sequence's committed page must be
        refused too — the writing row's own page index can't see it, the
        batch-wide ownership map can."""
        san = StepSanitizer(self.PS)
        a = _FakeSeq("a", num_tokens=9, pages=[3, 4])
        b = _FakeSeq("b", num_tokens=9, pages=[6, 7])
        # row 0 (seq a) claims a legal position but its write slot lands in
        # seq b's page 6, position 0 — committed history of b.
        batch = _FakeSpecBatch([a, b], [0], [8], [6 * self.PS])
        with pytest.raises(SanitizerError, match="owned by 'b'|owned by b"):
            san.on_spec_dispatch(batch)

    def test_recycled_request_id_does_not_inherit_stale_state(self):
        san = StepSanitizer(self.PS)
        old = _FakeSeq("r1", num_tokens=9, pages=[3, 4])
        batch = self._spec_step(san, old, k=2)
        san.on_spec_commit(batch, np.asarray([1]))
        assert san._stale["r1"]
        # a NEW sequence object reuses the id with fresh pages: the old
        # stale map must be dropped, not raised over
        fresh = _FakeSeq("r1", num_tokens=13, pages=[5, 6])
        san.on_decode_dispatch([fresh], np.asarray([12]), window=8)
        assert san._stale.get("r1", {}) == {}

    def test_scrap_page_writes_are_ignored(self):
        san = StepSanitizer(self.PS)
        seq = _FakeSeq("r1", num_tokens=9, pages=[3, 4])
        # slot < page_size -> scrap page routing, never an error
        batch = _FakeSpecBatch([seq], [0], [8], [5])
        san.on_spec_dispatch(batch)

    def test_finished_seqs_pruned(self):
        san = StepSanitizer(self.PS)
        seq = _FakeSeq("r1", num_tokens=9, pages=[3, 4])
        batch = self._spec_step(san, seq, k=2)
        san.on_spec_commit(batch, np.asarray([1]))
        assert "r1" in san._stale
        other = _FakeSeq("r2", num_tokens=5, pages=[5])
        san.on_decode_dispatch([other], np.asarray([4]), window=8)
        assert "r1" not in san._stale   # absent from a full batch = gone


class TestOutputGuardUnit:
    def test_out_of_vocab_token(self):
        san = StepSanitizer(8)
        with pytest.raises(SanitizerError, match="out of vocab"):
            san.check_outputs(np.asarray([[5, 900]]),
                              np.zeros((1, 2)), None, 512, 1)

    def test_inf_logprob(self):
        san = StepSanitizer(8)
        with pytest.raises(SanitizerError, match="non-finite"):
            san.check_outputs(np.asarray([[5, 6]]),
                              np.asarray([[0.0, np.inf]]), None, 512, 1)

    def test_emit_mask_ignores_rejected_columns(self):
        """Spec rows carry garbage past the accepted prefix — the guard
        must only check what the host consumes."""
        san = StepSanitizer(8)
        san.check_outputs(np.asarray([[5, -1, 99999]]),
                          np.asarray([[0.0, np.nan, np.inf]]),
                          np.asarray([1]), 512, 1)

    def test_padding_rows_ignored(self):
        san = StepSanitizer(8)
        san.check_outputs(np.asarray([[5], [-7]]),
                          np.asarray([[0.0], [np.nan]]), None, 512,
                          num_seqs=1)


# -- interleave sanitizer (KGCT_SANITIZE_INTERLEAVE) ---------------------------

import asyncio
import itertools
import threading
import types

from kubernetes_gpu_cluster_tpu.analysis.sanitize import (
    InterleaveSanitizer, build_interleave_sanitizer)
from kubernetes_gpu_cluster_tpu.engine import SamplingParams as _SP
from kubernetes_gpu_cluster_tpu.serving.async_engine import AsyncLLMEngine


class _ScriptedEngine:
    """Deterministic engine stand-in: emits ``n`` fixed tokens per request,
    one per step. The interleave sanitizer perturbs WHERE the loop and
    worker interleave, never WHAT the engine computes — a scripted engine
    makes that separation testable in milliseconds (no device, no jit)."""

    def __init__(self, n: int = 4):
        self.n = n
        self._live: dict = {}

    def has_unfinished_requests(self):
        return bool(self._live)

    def add_request(self, rid, ids, params, **kw):
        self._live[rid] = []

    def abort_request(self, rid):
        self._live.pop(rid, None)

    def export_held(self, rid):          # run_in_worker target in the test
        return f"held:{rid}"

    def step(self):
        outs = []
        for rid in list(self._live):
            toks = self._live[rid]
            toks.append(100 + len(toks))
            fin = len(toks) >= self.n
            outs.append(types.SimpleNamespace(
                request_id=rid, new_token_ids=[toks[-1]],
                output_token_ids=list(toks), finished=fin,
                finish_reason="length" if fin else None,
                new_logprobs=[], new_top_logprobs=[]))
            if fin:
                del self._live[rid]
        return outs


def _make_async_engine() -> AsyncLLMEngine:
    """Engine-free AsyncLLMEngine (the __new__ pattern): real worker
    thread, real _cv handshake, real interleave hooks — scripted steps."""
    a = AsyncLLMEngine.__new__(AsyncLLMEngine)
    a.engine = _ScriptedEngine()
    a.leader = None
    a.watchdog = None
    a._loop = None
    a._queues = {}
    a._reserved = set()
    a._inbox = []
    a._aborts = []
    a._handoffs = {}
    a._holds = set()
    a._resumes = {}
    a._arrival_t0s = {}
    a.on_import_fallback = None
    a._ops = []
    a._interleave = build_interleave_sanitizer()
    a._cv = threading.Condition()
    a._shutdown = False
    a._counter = itertools.count()
    a._thread = threading.Thread(target=a._worker, daemon=True,
                                 name="kgct-test-step-loop")
    return a


def _serve(n_requests: int = 3):
    """Run a small concurrent workload through the async engine; returns
    ({request_id: output tokens}, the engine's InterleaveSanitizer)."""
    a = _make_async_engine()
    loop = asyncio.new_event_loop()
    try:
        async def consume(rid):
            assert a.reserve_request_id(rid)
            toks = []
            async for chunk in a.generate(rid, [1, 2, 3], _SP(max_tokens=4)):
                toks = list(chunk.output_token_ids)
            # One worker-op crossing per request: the export seam path.
            held = await a.run_in_worker(lambda e: e.export_held(rid))
            assert held == f"held:{rid}"
            return toks

        async def go():
            a.start()
            outs = await asyncio.gather(
                *[consume(f"r{i}") for i in range(n_requests)])
            return {f"r{i}": outs[i] for i in range(n_requests)}

        return loop.run_until_complete(go()), a._interleave
    finally:
        a.shutdown()
        loop.close()


def _by_site(trace):
    sites: dict = {}
    for site, n, yielded in trace:
        sites.setdefault(site, []).append((n, yielded))
    return sites


class TestInterleaveSanitizer:
    def test_build_seam_reads_env(self, monkeypatch):
        monkeypatch.delenv("KGCT_SANITIZE_INTERLEAVE", raising=False)
        assert build_interleave_sanitizer() is None
        monkeypatch.setenv("KGCT_SANITIZE_INTERLEAVE", "0")
        assert build_interleave_sanitizer() is None
        monkeypatch.setenv("KGCT_SANITIZE_INTERLEAVE", "1")
        monkeypatch.setenv("KGCT_INTERLEAVE_SEED", "7")
        izer = build_interleave_sanitizer()
        assert isinstance(izer, InterleaveSanitizer) and izer.seed == 7

    def test_decisions_are_a_pure_function_of_seed_site_counter(self):
        a, b = InterleaveSanitizer(3), InterleaveSanitizer(3)
        sa = [a.decide("worker.wake") for _ in range(64)]
        assert sa == [b.decide("worker.wake") for _ in range(64)]
        sc = [InterleaveSanitizer(4).decide("worker.wake")
              for _ in range(64)]
        assert sa != sc                        # seed picks the schedule
        yielded = [y for y, _ in sa]
        assert any(yielded) and not all(yielded)   # perturbs, some sites

    def test_off_engine_holds_none_and_outputs_byte_identical(
            self, monkeypatch):
        monkeypatch.delenv("KGCT_SANITIZE_INTERLEAVE", raising=False)
        base, izer = _serve()
        assert izer is None                    # zero-cost hooks when off
        monkeypatch.setenv("KGCT_SANITIZE_INTERLEAVE", "1")
        monkeypatch.setenv("KGCT_INTERLEAVE_SEED", "3")
        perturbed, izer_on = _serve()
        assert izer_on is not None and izer_on.trace
        # Interleaving changed, outputs did not: the sanitizer perturbs
        # scheduling only — any output divergence IS a found race.
        assert perturbed == base

    def test_same_seed_replays_the_interleaving(self, monkeypatch):
        monkeypatch.setenv("KGCT_SANITIZE_INTERLEAVE", "1")
        monkeypatch.setenv("KGCT_INTERLEAVE_SEED", "3")
        out1, iz1 = _serve()
        out2, iz2 = _serve()
        assert out1 == out2
        s1, s2 = _by_site(iz1.trace), _by_site(iz2.trace)
        # Loop-side sites have workload-determined counts: exact replay.
        for site in ("generate.submit", "generate.stream"):
            assert s1[site] == s2[site], site
        # Worker-side wakeup counts depend on OS thread timing, but the
        # decision SEQUENCE is seed-deterministic: common prefix matches.
        for site in ("worker.wake", "worker.step"):
            k = min(len(s1[site]), len(s2[site]))
            assert k > 0 and s1[site][:k] == s2[site][:k], site
        # At least one sanctioned seam crossing actually yielded.
        assert any(y for _, _, y in iz1.trace)
        # A different seed drives a different schedule.
        monkeypatch.setenv("KGCT_INTERLEAVE_SEED", "11")
        out3, iz3 = _serve()
        assert out3 == out1                    # still race-free
        s3 = _by_site(iz3.trace)
        assert s3["generate.stream"] != s1["generate.stream"]
