"""Scheduler admission/preemption policy regression tests."""

import pytest

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine.scheduler import Scheduler
from kubernetes_gpu_cluster_tpu.engine.sampling_params import SamplingParams
from kubernetes_gpu_cluster_tpu.engine.sequence import (
    FinishReason, Sequence, SequenceStatus)


def _cfg(num_pages=8, page_size=4, max_num_seqs=4, decode_window=1):
    return EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=page_size, num_pages=num_pages),
        scheduler=SchedulerConfig(max_num_seqs=max_num_seqs,
                                  max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(16, 32, 64),
                                  decode_window=decode_window))


def _seq(rid, n_prompt, max_tokens=64):
    return Sequence(rid, list(range(1, n_prompt + 1)),
                    SamplingParams(max_tokens=max_tokens))


class TestAdmission:
    def test_oversized_prompt_rejected_up_front(self):
        """A prompt needing more pages than the whole pool must raise, not
        busy-spin forever (review finding: schedule() returned None while
        has_work() stayed True)."""
        cfg = _cfg(num_pages=4, page_size=4)   # 3 usable pages = 12 tokens
        sched = Scheduler(cfg, 4)
        with pytest.raises(ValueError, match="KV pages"):
            sched.add(_seq("big", 13))
        # A fitting prompt is accepted and schedulable.
        sched.add(_seq("ok", 12))
        assert sched.schedule() is not None

    def test_no_preemption_for_waiting_sequences(self):
        """Admitting a waiting sequence must never evict running ones (review
        finding: preempt-at-admission churned full recomputes)."""
        cfg = _cfg(num_pages=9, page_size=4, max_num_seqs=8)  # 8 usable pages
        sched = Scheduler(cfg, 9)
        for i in range(4):
            sched.add(_seq(f"run-{i}", 8))     # 2 pages each -> pool full
        batch = sched.schedule()
        assert batch.kind == "prefill" and len(batch.seqs) == 4
        sched.add(_seq("late", 8))
        # Pool is full: the late arrival must wait; the step must be a decode
        # of the 4 running sequences, with nobody preempted.
        batch = sched.schedule()
        assert batch.kind == "decode" and len(batch.seqs) == 4
        assert sched.num_preemptions == 0
        assert [s.request_id for s in sched.running] == [f"run-{i}" for i in range(4)]

    def test_grown_sequence_at_pool_capacity_finishes(self):
        """A recomputed sequence grown past total pool capacity terminates at
        LENGTH instead of hanging the engine."""
        cfg = _cfg(num_pages=3, page_size=4)   # 2 usable pages = 8 tokens
        sched = Scheduler(cfg, 3)
        seq = _seq("grown", 6)
        sched.add(seq)
        assert sched.schedule() is not None    # prefill at 6 tokens (2 pages)
        # Simulate preempt-recompute growth past capacity: 9 tokens > 8.
        for t in (7, 8, 9):
            seq.append_token(t)
        sched.running.remove(seq)
        sched.allocator.free(seq.pages)
        seq.pages = []
        seq.status = SequenceStatus.PREEMPTED
        sched.waiting.appendleft(seq)
        assert sched.schedule() is None
        assert seq.status == SequenceStatus.FINISHED
        assert seq.finish_reason == FinishReason.LENGTH
        assert not sched.has_work()
        # The engine must be able to surface a finished event for it (review
        # finding: generate() raised KeyError / server clients hung).
        assert sched.terminally_finished == [seq]

    def test_engine_emits_output_for_capacity_terminated_seq(self):
        """End-to-end: a scheduler-terminated sequence still produces a
        finished RequestOutput through LLMEngine.step()."""
        from kubernetes_gpu_cluster_tpu.engine import LLMEngine

        cfg = _cfg(num_pages=3, page_size=4)   # 2 usable pages = 8 tokens
        eng = LLMEngine(cfg)
        seq = _seq("grown", 6)
        eng.scheduler.add(seq)
        for t in (7, 8, 9):                    # grown past 8-token capacity
            seq.append_token(t)
        outs = eng.step()
        assert [o.request_id for o in outs] == ["grown"]
        assert outs[0].finished and outs[0].finish_reason == "length"
        assert not eng.has_unfinished_requests()


class TestAbort:
    def test_abort_waiting_sets_finish_reason(self):
        sched = Scheduler(_cfg(), 8)
        seq = _seq("a", 4)
        sched.add(seq)
        assert sched.abort("a")
        assert seq.status == SequenceStatus.FINISHED
        assert seq.finish_reason == FinishReason.ABORT
        assert not sched.has_work()

    def test_abort_running_frees_pages_and_finishes(self):
        sched = Scheduler(_cfg(num_pages=8, page_size=4), 8)
        seq = _seq("r", 8)
        sched.add(seq)
        sched.schedule()
        free_before = sched.allocator.num_free
        assert sched.abort("r")
        assert seq.finish_reason == FinishReason.ABORT
        assert sched.allocator.num_free == free_before + 2
        assert not sched.has_work()

    def test_abort_unknown_returns_false(self):
        sched = Scheduler(_cfg(), 8)
        assert not sched.abort("nope")


class TestPreemptionInDecode:
    def test_decode_preempts_youngest_when_pool_exhausted(self):
        """Decode-path preemption (the legitimate one) still works: when a
        running sequence needs a new page and none is free, the youngest is
        evicted and re-queued."""
        cfg = _cfg(num_pages=3, page_size=2, max_num_seqs=4)  # 2 usable pages
        sched = Scheduler(cfg, 3)
        a, b = _seq("a", 2), _seq("b", 2)
        sched.add(a)
        sched.add(b)
        assert sched.schedule().kind == "prefill"   # each takes 1 page
        a.append_token(5)
        b.append_token(6)
        # Next decode: both need a second page; only 0 free -> preempt b.
        batch = sched.schedule()
        assert batch.kind == "decode"
        assert [s.request_id for s in batch.seqs] == ["a"]
        assert sched.num_preemptions == 1
        assert b.status == SequenceStatus.PREEMPTED
        assert sched.waiting[0] is b


class TestDecodeWindow:
    def test_window_preallocates_pages(self):
        """With decode_window=W the decode schedule must grow each sequence's
        page list to cover all W on-device KV writes up front."""
        cfg = _cfg(num_pages=9, page_size=4, decode_window=6)
        sched = Scheduler(cfg, 9)
        seq = _seq("w", 4)       # 1 page for the prompt
        sched.add(seq)
        assert sched.schedule().kind == "prefill"
        seq.append_token(5)
        batch = sched.schedule()
        assert batch.kind == "decode"
        # positions 4..9 -> 10 slots -> 3 pages of 4
        assert len(seq.pages) == 3
