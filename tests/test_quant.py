"""Weight-only quantization ladder (int8 W8A16 / int4 W4A16): numerics +
engine integration.

Quality bars, both enforced on the debug models:

- int8 (per-output-channel): logits cosine vs the full-precision model
  > 0.999, unchanged from the seed.
- int4 (group-wise, packed nibbles): two gates. (1) EXACTNESS — the
  dequant-fused matmul path must match an explicit dequantize-then-matmul
  reference to float tolerance; this is the implementation-bug gate (a
  wrong scale axis or packing order collapses it). (2) the same
  cosine-vs-bf16-logits test as int8, thresholded at the 4-bit
  round-to-nearest ERROR FLOOR: on iid-Gaussian random weights (the
  debug models — the worst case for 4-bit RTN, with none of the structure
  real checkpoints have) the per-matmul relative error is
  ~amax/(7*sqrt(12)*sigma) ~= 11%, which lands logits cosine at ~0.95;
  measured 0.947-0.955 across the debug models. The 0.94 gate pins that
  the implementation achieves that floor — quantization-scheme bugs land
  far below it — while 0.999 is arithmetically unreachable for ANY
  16-level symmetric quantizer on this weight distribution.

Structural bars: packing round-trips bit-exactly, group scales survive
row-sharding (slice-quantize == global quantize on aligned boundaries),
every quantized leaf has a sharding/pp spec, the engine serves int4
deterministically, and the packed footprint is REALLY half: buffer-size
accounting over the uploaded params puts int4 matmul bytes <= 0.55x int8's,
with no dequantized full-resolution copy anywhere in the pytree.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.ops.quant import (QUANT_LAYER_KEYS,
                                                  int4_matmul_xla,
                                                  pack_int4,
                                                  quantize_params,
                                                  quantize_tensor,
                                                  quantize_tensor_int4,
                                                  unpack_int4)

# Cosine-vs-full-precision gate per rung (rationale in module docstring).
COSINE_GATE = {"int8": 0.999, "int4": 0.94}
# debug models have 128-dim hidden / 256-dim ff: group 128 divides both.
GROUP = 128


def _quant_copy(params, method):
    q = {**params, "layers": dict(params["layers"])}
    return quantize_params(q, method, GROUP)


def test_quantize_tensor_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    w_q, scale = quantize_tensor(w)
    assert w_q.dtype == np.int8 and scale.shape == (128,)
    deq = w_q.astype(np.float32) * scale[None, :]
    # max error bounded by half a quantization step per channel
    assert np.max(np.abs(deq - w)) <= np.max(scale) * 0.51


def test_quantize_tensor_stacked_moe_shape():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((3, 4, 16, 8)).astype(np.float32)  # [L, E, in, out]
    w_q, scale = quantize_tensor(w)
    assert w_q.shape == w.shape and scale.shape == (3, 4, 8)


def test_int4_pack_unpack_roundtrip():
    rng = np.random.default_rng(2)
    q = rng.integers(-8, 8, (3, 64, 16)).astype(np.int8)
    packed = pack_int4(q)
    assert packed.dtype == np.int8 and packed.shape == (3, 32, 16)
    np.testing.assert_array_equal(unpack_int4(packed), q)
    # jnp round-trip agrees bit-for-bit with numpy
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(jnp.asarray(packed))), q)


def test_int4_group_quant_roundtrip_error():
    rng = np.random.default_rng(3)
    w = rng.standard_normal((256, 32)).astype(np.float32)
    packed, scale = quantize_tensor_int4(w, 64)
    assert packed.shape == (128, 32) and scale.shape == (4, 32)
    deq = (unpack_int4(packed).astype(np.float32).reshape(4, 64, 32)
           * scale[:, None, :]).reshape(256, 32)
    # max error bounded by half a step of the OWN group's scale
    step = np.repeat(scale, 64, axis=0)
    assert np.max(np.abs(deq - w) / step) <= 0.51


def test_int4_stacked_moe_shape():
    rng = np.random.default_rng(4)
    w = rng.standard_normal((2, 3, 128, 8)).astype(np.float32)
    packed, scale = quantize_tensor_int4(w, 32)
    assert packed.shape == (2, 3, 64, 8) and scale.shape == (2, 3, 4, 8)


def test_int4_rejects_unaligned_input_dim():
    with pytest.raises(ValueError, match="not divisible"):
        quantize_tensor_int4(np.zeros((100, 8), np.float32), 64)


def test_int4_shard_slice_matches_global():
    """Row-sharding contract (engine/weights.py): a shard whose input-row
    slice aligns with group boundaries reproduces the global packed bytes
    and scales bit-for-bit from its slice alone."""
    rng = np.random.default_rng(5)
    gs = 32
    w = rng.standard_normal((256, 16)).astype(np.float32)
    packed, scale = quantize_tensor_int4(w, gs)
    for r0, r1 in ((0, 128), (128, 256), (64, 192)):
        p_s, s_s = quantize_tensor_int4(w[r0:r1], gs)
        np.testing.assert_array_equal(p_s, packed[r0 // 2:r1 // 2])
        np.testing.assert_array_equal(s_s, scale[r0 // gs:r1 // gs])


def test_int4_fused_matmul_matches_dequant_reference():
    """The no-bugs gate: the fused path (group-contracted einsum, scales on
    the f32 partials) equals explicit dequantize-then-matmul."""
    rng = np.random.default_rng(6)
    K, N, T, gs = 256, 64, 7, 64
    w = rng.standard_normal((K, N)).astype(np.float32)
    x = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
    packed, scale = quantize_tensor_int4(w, gs)
    deq = (unpack_int4(packed).astype(np.float32).reshape(K // gs, gs, N)
           * scale[:, None, :]).reshape(K, N)
    ref = np.asarray(x) @ deq
    got = np.asarray(int4_matmul_xla(x, jnp.asarray(packed),
                                     jnp.asarray(scale)))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)


# Full-precision params + reference logits per model, computed once and
# shared across the int8/int4 parametrizations (tier-1 time budget).
_REF_CACHE: dict = {}


def _ref_logits(model, cfg, logits_of):
    if model not in _REF_CACHE:
        params = model_lib.init_params(cfg, jax.random.key(0))
        _REF_CACHE[model] = (params, logits_of(params))
    return _REF_CACHE[model]


@pytest.mark.parametrize("method", ["int8", "int4"])
@pytest.mark.parametrize("model", ["debug-tiny", "debug-moe"])
def test_logits_close_to_full_precision(model, method):
    cfg = get_model_config(model).replace(quant_group_size=GROUP)
    T = 6
    tokens = jnp.arange(T, dtype=jnp.int32) + 3
    meta = model_lib.PrefillMeta(
        seg_ids=jnp.zeros((T,), jnp.int32),
        positions=jnp.arange(T, dtype=jnp.int32),
        slot_mapping=jnp.arange(T, dtype=jnp.int32) + 8,
        logits_indices=jnp.asarray([T - 1], jnp.int32))
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
    cache = CacheConfig(page_size=8, num_pages=9)

    def logits_of(p):
        kv = allocate_kv_cache(cfg, cache, 9)
        h, _, _ = model_lib.forward_prefill(p, cfg, tokens, meta, kv,
                                            use_pallas=False)
        return np.asarray(model_lib.compute_logits(p, cfg, h))[0]

    params, ref = _ref_logits(model, cfg, logits_of)
    qparams = _quant_copy(params, method)
    for key in QUANT_LAYER_KEYS:
        assert qparams["layers"][key].dtype == jnp.int8
        assert key + "_scale" in qparams["layers"]
        if method == "int4":
            w, s = qparams["layers"][key], qparams["layers"][key + "_scale"]
            assert w.shape[-2] * 2 == params["layers"][key].shape[-2]
            assert s.ndim == w.ndim          # group axis present
    got = logits_of(qparams)
    cos = np.dot(ref, got) / (np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > COSINE_GATE[method], (method, cos)


def test_engine_serves_quantized_int8():
    cfg = EngineConfig(
        model=get_model_config("debug-tiny").replace(quantization="int8"),
        cache=CacheConfig(page_size=8, num_pages=33),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(32, 64)))
    eng = LLMEngine(cfg)
    outs = eng.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                            temperature=0.0))
    assert all(len(o.output_token_ids) == 8 for o in outs)
    # determinism under quantization
    eng2 = LLMEngine(cfg)
    outs2 = eng2.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                              temperature=0.0))
    assert [o.output_token_ids for o in outs] == \
        [o.output_token_ids for o in outs2]


def test_engine_serves_quantized_int4():
    """int4 end to end: the engine builds, compiles the dequant-fused
    programs, serves, and repeated greedy generation is deterministic.
    Scheduler/spec/mixed behavior is untouched by construction — the quant
    rung only changes the params pytree and _dot (same budget-friendly
    check as int8: full generation runs, stop conditions identical)."""
    cfg = EngineConfig(
        model=get_model_config("debug-tiny").replace(quantization="int4"),
        cache=CacheConfig(page_size=8, num_pages=33),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(32, 64)))
    eng = LLMEngine(cfg)
    outs = eng.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                            temperature=0.0))
    assert all(len(o.output_token_ids) == 8 for o in outs)
    outs2 = eng.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                             temperature=0.0))
    assert [o.output_token_ids for o in outs] == \
        [o.output_token_ids for o in outs2]


@pytest.mark.parametrize("method", ["int8", "int4"])
def test_quantized_param_shardings_cover_scales(method):
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh, param_shardings
    cfg = get_model_config("debug-moe").replace(quantization=method,
                                                quant_group_size=32)
    mesh = make_mesh(tp=2, ep=2, dp=2)
    params = model_lib.init_params(cfg, jax.random.key(0))
    sh = param_shardings(mesh, cfg)
    # every quantized leaf has a matching sharding entry
    flat_p = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(params)}
    flat_s = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(sh)}
    assert set(flat_p) == set(flat_s), (
        set(flat_p) ^ set(flat_s))
    if method == "int8":
        # One real placement proves the specs are device_put-compatible;
        # int4 placement on real tp/pp/ep meshes is already covered
        # bit-for-bit by tests/test_weights_streamed.py (cheaper here to
        # check the spec SETS only — tier-1 time budget).
        placed = jax.device_put(params, sh)
        assert placed["layers"]["wq"].dtype == jnp.int8
    else:
        # group axis must shard like the weight's input axis
        assert sh["layers"]["wo_scale"].spec[1] == "tp"
        assert sh["layers"]["w_down_scale"].spec[2] == "tp"


@pytest.mark.parametrize("method", ["int8", "int4"])
def test_quantized_pp_specs_cover_scales(method):
    """quant + pipeline parallelism: the shard_map spec pytree must match
    the quantized params pytree (regression: scales were missing from
    parallel/pp.py's specs while sharding.py had them; int4 adds the group
    axis, whose specs must track the weight's input-axis sharding)."""
    from kubernetes_gpu_cluster_tpu.parallel.pp import param_pp_specs
    for model in ("debug-tiny", "debug-moe"):
        cfg = get_model_config(model).replace(quantization=method,
                                              quant_group_size=32)
        params = model_lib.init_params(cfg, jax.random.key(0))
        specs = param_pp_specs(cfg)
        flat_p = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(params)}
        flat_s = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(specs)}
        assert flat_p == flat_s, (model, flat_p ^ flat_s)


def test_opt_class_int8_specs_and_engine():
    """OPT-class flags (layernorm/learned-pos/biased-relu MLP) + int8: the
    spec pytrees must match the quantized params pytree (no w_gate, biased
    extras present), and the engine serves the quantized model."""
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh, param_shardings
    from kubernetes_gpu_cluster_tpu.parallel.pp import param_pp_specs

    cfg = get_model_config(
        "debug-tiny", norm_type="layernorm", pos_embedding="learned",
        mlp_type="mlp", mlp_act="relu", linear_bias=True,
        attention_bias=True).replace(quantization="int8")
    params = model_lib.init_params(cfg, jax.random.key(0))
    assert "w_gate" not in params["layers"]
    assert "pos_embed" in params and "final_norm_b" in params

    flat_p = {jax.tree_util.keystr(k) for k, _ in
              jax.tree_util.tree_leaves_with_path(params)}
    for specs in (param_shardings(make_mesh(tp=2), cfg), param_pp_specs(cfg)):
        flat_s = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(specs)}
        assert flat_p == flat_s, flat_p ^ flat_s

    eng = LLMEngine(EngineConfig(
        model=cfg, cache=CacheConfig(page_size=8, num_pages=32),
        scheduler=SchedulerConfig(max_num_seqs=2, max_prefill_tokens=64,
                                  decode_buckets=(1, 2),
                                  prefill_buckets=(32, 64), decode_window=2)))
    out = eng.generate([[1, 2, 3]], SamplingParams(max_tokens=4,
                                                   temperature=0.0))[0]
    assert len(out.output_token_ids) == 4


def _matmul_bytes(params):
    """Buffer bytes of the quantized-matmul surface (weights + scales) — the
    SAME accounting the bench reports (bench._param_bytes), so this pin and
    the bench's `matmul_weight_bytes` field cannot drift."""
    import bench
    return bench._param_bytes(params)[1]


@pytest.mark.parametrize("model", ["debug-tiny", "debug-moe"])
def test_int4_buffer_bytes_half_of_int8_no_dequant_copy(model):
    """The acceptance A/B, by buffer-size accounting (not vibes): packed
    int4 matmul bytes (incl. group scales) <= 0.55x int8's, and the pytree
    holds NO dequantized copy — every quantized weight leaf is int8 storage
    at the PACKED shape, every scale is the small f32 side-table."""
    base = get_model_config(model).replace(quant_group_size=GROUP)
    p8 = model_lib.init_params(base.replace(quantization="int8"),
                               jax.random.key(0))
    p4 = model_lib.init_params(base.replace(quantization="int4"),
                               jax.random.key(0))
    b8, b4 = _matmul_bytes(p8), _matmul_bytes(p4)
    assert b4 <= 0.55 * b8, (b4, b8)
    assert b4 >= 0.45 * b8, (b4, b8)           # sanity: really packed, not 0
    for key in QUANT_LAYER_KEYS:
        if key not in p4["layers"]:
            continue
        w4, w8 = p4["layers"][key], p8["layers"][key]
        assert w4.dtype == jnp.int8
        assert w4.shape[-2] * 2 == w8.shape[-2]          # nibble-packed
        s4 = p4["layers"][key + "_scale"]
        assert s4.dtype == jnp.float32
        assert s4.shape[-2] == w8.shape[-2] // GROUP     # one row per group


def test_roofline_int4_weight_stream_half_of_int8():
    """bench roofline accounting: int4 weight_stream_bytes reflects packed
    bytes + scales — about half of int8's, never more than 0.55x."""
    import bench
    for model in ("llama-3-8b", "qwen3-14b", "mixtral-8x7b", "debug-tiny"):
        mcfg = get_model_config(model)
        s8 = bench._weight_stream_bytes(mcfg, "int8")
        s4 = bench._weight_stream_bytes(mcfg, "int4")
        assert 0.45 * s8 <= s4 <= 0.55 * s8, (model, s4, s8)
        ctx = 512
        r8 = bench._roofline(mcfg, "int8", 8, ctx)
        r4 = bench._roofline(mcfg, "int4", 8, ctx)
        assert r4["weight_stream_bytes"] == s4
        assert r4["kv_bytes_per_step"] == r8["kv_bytes_per_step"]  # KV bf16
