"""Int8 weight-only quantization: numerics + engine integration.

Quality bar: per-output-channel symmetric int8 on the big matmuls must keep
logits close to the full-precision model (cosine > 0.999 on the debug model)
and must not change greedy decoding behavior structurally (the engine runs,
shapes/stop conditions identical).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.ops.quant import (QUANT_LAYER_KEYS,
                                                  quantize_params,
                                                  quantize_tensor)


def test_quantize_tensor_roundtrip():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    w_q, scale = quantize_tensor(w)
    assert w_q.dtype == np.int8 and scale.shape == (128,)
    deq = w_q.astype(np.float32) * scale[None, :]
    # max error bounded by half a quantization step per channel
    assert np.max(np.abs(deq - w)) <= np.max(scale) * 0.51


def test_quantize_tensor_stacked_moe_shape():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((3, 4, 16, 8)).astype(np.float32)  # [L, E, in, out]
    w_q, scale = quantize_tensor(w)
    assert w_q.shape == w.shape and scale.shape == (3, 4, 8)


@pytest.mark.parametrize("model", ["debug-tiny", "debug-moe"])
def test_logits_close_to_full_precision(model):
    cfg = get_model_config(model)
    params = model_lib.init_params(cfg, jax.random.key(0))
    import copy
    qparams = quantize_params(
        jax.tree.map(lambda x: x, {**params,
                                   "layers": dict(params["layers"])}),
        "int8")
    for key in QUANT_LAYER_KEYS:
        assert qparams["layers"][key].dtype == jnp.int8
        assert key + "_scale" in qparams["layers"]

    T = 6
    tokens = jnp.arange(T, dtype=jnp.int32) + 3
    meta = model_lib.PrefillMeta(
        seg_ids=jnp.zeros((T,), jnp.int32),
        positions=jnp.arange(T, dtype=jnp.int32),
        slot_mapping=jnp.arange(T, dtype=jnp.int32) + 8,
        logits_indices=jnp.asarray([T - 1], jnp.int32))
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
    cache = CacheConfig(page_size=8, num_pages=9)

    def logits_of(p):
        kv = allocate_kv_cache(cfg, cache, 9)
        h, _, _ = model_lib.forward_prefill(p, cfg, tokens, meta, kv,
                                            use_pallas=False)
        return np.asarray(model_lib.compute_logits(p, cfg, h))[0]

    ref = logits_of(params)
    got = logits_of(qparams)
    cos = np.dot(ref, got) / (np.linalg.norm(ref) * np.linalg.norm(got))
    assert cos > 0.999, cos


def test_engine_serves_quantized():
    cfg = EngineConfig(
        model=get_model_config("debug-tiny").replace(quantization="int8"),
        cache=CacheConfig(page_size=8, num_pages=33),
        scheduler=SchedulerConfig(max_num_seqs=4, max_prefill_tokens=64,
                                  decode_buckets=(1, 2, 4),
                                  prefill_buckets=(32, 64)))
    eng = LLMEngine(cfg)
    outs = eng.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                            temperature=0.0))
    assert all(len(o.output_token_ids) == 8 for o in outs)
    # determinism under quantization
    eng2 = LLMEngine(cfg)
    outs2 = eng2.generate([[1, 2, 3], [7, 8]], SamplingParams(max_tokens=8,
                                                              temperature=0.0))
    assert [o.output_token_ids for o in outs] == \
        [o.output_token_ids for o in outs2]


def test_quantized_param_shardings_cover_scales():
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh, param_shardings
    cfg = get_model_config("debug-moe").replace(quantization="int8")
    mesh = make_mesh(tp=2, ep=2, dp=2)
    params = model_lib.init_params(cfg, jax.random.key(0))
    sh = param_shardings(mesh, cfg)
    # every quantized leaf has a matching sharding entry
    flat_p = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(params)}
    flat_s = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(sh)}
    assert set(flat_p) == set(flat_s), (
        set(flat_p) ^ set(flat_s))
    placed = jax.device_put(params, sh)
    assert placed["layers"]["wq"].dtype == jnp.int8


def test_quantized_pp_specs_cover_scales():
    """int8 + pipeline parallelism: the shard_map spec pytree must match the
    quantized params pytree (regression: scales were missing from
    parallel/pp.py's specs while sharding.py had them)."""
    from kubernetes_gpu_cluster_tpu.parallel.pp import param_pp_specs
    for model in ("debug-tiny", "debug-moe"):
        cfg = get_model_config(model).replace(quantization="int8")
        params = model_lib.init_params(cfg, jax.random.key(0))
        specs = param_pp_specs(cfg)
        flat_p = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(params)}
        flat_s = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(specs)}
        assert flat_p == flat_s, (model, flat_p ^ flat_s)


def test_opt_class_int8_specs_and_engine():
    """OPT-class flags (layernorm/learned-pos/biased-relu MLP) + int8: the
    spec pytrees must match the quantized params pytree (no w_gate, biased
    extras present), and the engine serves the quantized model."""
    from kubernetes_gpu_cluster_tpu.parallel import make_mesh, param_shardings
    from kubernetes_gpu_cluster_tpu.parallel.pp import param_pp_specs

    cfg = get_model_config(
        "debug-tiny", norm_type="layernorm", pos_embedding="learned",
        mlp_type="mlp", mlp_act="relu", linear_bias=True,
        attention_bias=True).replace(quantization="int8")
    params = model_lib.init_params(cfg, jax.random.key(0))
    assert "w_gate" not in params["layers"]
    assert "pos_embed" in params and "final_norm_b" in params

    flat_p = {jax.tree_util.keystr(k) for k, _ in
              jax.tree_util.tree_leaves_with_path(params)}
    for specs in (param_shardings(make_mesh(tp=2), cfg), param_pp_specs(cfg)):
        flat_s = {jax.tree_util.keystr(k) for k, _ in
                  jax.tree_util.tree_leaves_with_path(specs)}
        assert flat_p == flat_s, flat_p ^ flat_s

    eng = LLMEngine(EngineConfig(
        model=cfg, cache=CacheConfig(page_size=8, num_pages=32),
        scheduler=SchedulerConfig(max_num_seqs=2, max_prefill_tokens=64,
                                  decode_buckets=(1, 2),
                                  prefill_buckets=(32, 64), decode_window=2)))
    out = eng.generate([[1, 2, 3]], SamplingParams(max_tokens=4,
                                                   temperature=0.0))[0]
    assert len(out.output_token_ids) == 4
