"""Speculative decoding subsystem (engine/spec/) correctness pins.

The bars, in order of importance:

1. LOSSLESSNESS. Greedy output with spec on is byte-identical to spec off
   (exact-match acceptance), including under a proposer that drafts pure
   garbage — every draft rejects, and the resample IS the greedy token.
   Sampled output preserves the target distribution exactly (seeded
   chi-square over >= 10k draws on a toy vocab at the ops level).
2. ROLLBACK. Rejected drafts leave no trace: sequence state rewinds to
   exactly the accepted prefix, the rejected KV slots are overwritten by
   later steps before any read, and pages fully return to the pool.
3. PLUMBING. Proposer lookup rules; kgct_spec_* metrics on the serving
   /metrics render; per-step "spec" trace events.
"""

import numpy as np
import pytest

import jax

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.engine.spec import DraftProposer, NgramProposer
from kubernetes_gpu_cluster_tpu.models import llama as model_lib

_MODEL = get_model_config("debug-tiny")
_PARAMS = model_lib.init_params(_MODEL, jax.random.key(7))


def make_engine(spec: bool, k: int = 4, num_pages: int = 128,
                max_seqs: int = 4, decode_window: int = 8):
    cfg = EngineConfig(
        model=_MODEL,
        cache=CacheConfig(page_size=8, num_pages=num_pages),
        scheduler=SchedulerConfig(
            max_num_seqs=max_seqs, max_prefill_tokens=256,
            decode_buckets=(1, 2, 4), prefill_buckets=(32, 64, 128, 256),
            decode_window=decode_window,
            spec_decode_enabled=spec, num_speculative_tokens=k))
    return LLMEngine(cfg, params=_PARAMS)


REPETITIVE = [7, 3, 9, 11] * 8          # n-gram matches everywhere
PLAIN = [5, 99, 23, 44, 17, 301, 12]    # no lookup structure


class TestNgramProposer:
    def test_matches_most_recent_continuation(self):
        p = NgramProposer(k=3, ngram_max=2, ngram_min=1)
        #            [1, 2] ... [1, 2] -> continuation 7, 8, 9
        assert p.propose([1, 2, 7, 8, 9, 5, 1, 2]) == [7, 8, 9]

    def test_prefers_longer_ngram(self):
        p = NgramProposer(k=2, ngram_max=3, ngram_min=1)
        # 3-gram [1, 2, 3] matches at the start (-> 10, 11); the 1-gram
        # [3] also matches later (-> 99) but the longer match wins.
        assert p.propose([1, 2, 3, 10, 11, 3, 99, 1, 2, 3]) == [10, 11]

    def test_most_recent_occurrence_wins(self):
        p = NgramProposer(k=1, ngram_max=1, ngram_min=1)
        assert p.propose([5, 1, 5, 2, 5, 3, 5]) == [3]

    def test_no_match_returns_empty(self):
        p = NgramProposer(k=4)
        assert p.propose([1, 2, 3, 4, 5]) == []
        assert p.propose([1]) == []

    def test_continuation_may_cover_the_suffix_again(self):
        # match at index 0: the continuation [9, 4] includes the repeated
        # suffix token — drafts may run past the matched gram, that's the
        # point of k > 1.
        p = NgramProposer(k=8, ngram_max=1, ngram_min=1)
        assert p.propose([4, 9, 4]) == [9, 4]

    def test_validation(self):
        with pytest.raises(ValueError):
            NgramProposer(k=0)
        with pytest.raises(ValueError):
            NgramProposer(k=2, ngram_max=1, ngram_min=2)


class _GarbageProposer(DraftProposer):
    """Always drafts the same (almost surely wrong) token — forces a
    rejection at draft position 0 on nearly every spec step."""

    def __init__(self, k, token=1):
        super().__init__(k)
        self.token = token

    def propose(self, token_ids):
        return [self.token] * self.k


class TestGreedyByteIdentity:
    def test_spec_on_off_identical(self):
        sp = SamplingParams(max_tokens=24, temperature=0.0)
        prompts = [list(REPETITIVE), list(PLAIN), [2, 4] * 10]
        ref = [o.output_token_ids
               for o in make_engine(False).generate(prompts, sp)]
        eng = make_engine(True)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        # the run actually exercised spec steps (repetitive greedy decode
        # falls into cycles the n-gram proposer drafts correctly)
        assert eng.obs.step_kind_counts["spec"] > 0
        assert eng.obs.spec_accepted_tokens > 0
        # all pages returned
        alloc = eng.scheduler.allocator
        assert alloc.num_free == alloc.num_pages - 1

    def test_all_rejected_drafts_identical(self):
        """Garbage drafts reject at position 0 every step: each spec step
        emits exactly the one resampled (= greedy) token, so the output —
        and every later step built on the rolled-back state — must stay
        byte-identical to non-spec greedy."""
        sp = SamplingParams(max_tokens=16, temperature=0.0)
        prompts = [list(REPETITIVE), list(PLAIN)]
        ref = [o.output_token_ids
               for o in make_engine(False).generate(prompts, sp)]
        eng = make_engine(True)
        eng.scheduler.spec_proposer = _GarbageProposer(4, token=1)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        assert eng.obs.step_kind_counts["spec"] > 0
        # near-total rejection (token 1 may coincide with an argmax once in
        # a blue moon; the bound just pins "mostly rejected")
        assert eng.obs.spec_accepted_tokens <= eng.obs.spec_drafted_tokens / 4

    def test_eos_mid_spec_window_stops_exactly(self):
        """A stop token inside the accepted prefix truncates the emitted
        window exactly like the decode path (tokens past the stop are
        discarded, finish_reason is stop)."""
        probe = make_engine(False).generate(
            [list(REPETITIVE)], SamplingParams(max_tokens=8,
                                               temperature=0.0))[0]
        eos = probe.output_token_ids[4]   # fires mid-run, not at step 0
        ref_eng = make_engine(False)
        ref_eng.eos_token_id = eos
        sp = SamplingParams(max_tokens=24, temperature=0.0)
        ref = ref_eng.generate([list(REPETITIVE)], sp)[0]
        eng = make_engine(True)
        eng.eos_token_id = eos
        out = eng.generate([list(REPETITIVE)], sp)[0]
        assert out.output_token_ids == ref.output_token_ids
        assert out.finish_reason == ref.finish_reason


class TestRollback:
    def test_state_rewinds_and_slots_reused(self):
        """Rollback pin: run a spec engine whose drafts are certain to be
        rejected, then keep generating — the rejected drafts' KV slots
        (written by the verify program at positions past the committed
        length) must be reusable, i.e. later steps overwrite them and the
        continued generation still matches the oracle token-for-token. Also
        pins the host-side rewind: after each spec step the sequence holds
        exactly accepted+1 new tokens."""
        eng = make_engine(True, k=3)
        eng.scheduler.spec_proposer = _GarbageProposer(3, token=2)
        sp = SamplingParams(max_tokens=20, temperature=0.0)
        eng.add_request("r", list(REPETITIVE), sp)
        seq = eng.scheduler.waiting[0]
        lens = []
        while eng.has_unfinished_requests():
            before = seq.num_output_tokens
            eng.step()
            lens.append(seq.num_output_tokens - before)
        # spec steps with all-rejected drafts advance by exactly 1 token
        assert eng.obs.step_kind_counts["spec"] > 0
        ref = make_engine(False).generate([list(REPETITIVE)], sp)[0]
        assert seq.output_token_ids == ref.output_token_ids
        alloc = eng.scheduler.allocator
        assert alloc.num_free == alloc.num_pages - 1

    def test_verify_kv_append_matches_oracle_pool(self):
        """Accepted drafts' KV written by the verify program must equal the
        KV a plain decode would have written: after generation, replaying
        the full sequence through a fresh prefill must reproduce the same
        next-token argmax as continuing the spec engine (an indirect but
        end-to-end pin that the multi-token append committed the right
        vectors into the right slots)."""
        sp = SamplingParams(max_tokens=12, temperature=0.0)
        eng = make_engine(True)
        out = eng.generate([list(REPETITIVE)], sp)[0]
        # teacher-forcing oracle over prompt+output
        from tests.test_model import _prefill_whole
        logits, _, _ = _prefill_whole(_MODEL, eng.params,
                                      list(REPETITIVE) + out.output_token_ids)
        want = int(np.argmax(np.asarray(logits)))
        cont = make_engine(False).generate(
            [list(REPETITIVE) + out.output_token_ids],
            SamplingParams(max_tokens=1, temperature=0.0))[0]
        assert cont.output_token_ids[0] == want


class TestDistributionPreservation:
    def test_rejection_sampling_chi_square(self):
        """Seeded statistical pin: the first emitted token of a verify step
        must be distributed EXACTLY as the target softmax, regardless of
        what the draft was. >= 10k independent draws on a toy vocab, plain
        chi-square against the analytic target (df = V-1 = 15; 60 is ~8
        sigma above the expectation of 15 — loose enough to never flake,
        tight enough that any bias in accept/resample fails instantly)."""
        from kubernetes_gpu_cluster_tpu.ops.sampling import spec_verify_sample
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        B, S, V = 12000, 2, 16
        row = (rng.standard_normal(V) * 1.5).astype(np.float32)
        target = np.exp(row - row.max())
        target /= target.sum()
        draft_tok = int(np.argsort(target)[-2])   # 2nd most likely
        logits = jnp.broadcast_to(jnp.asarray(row), (B, S, V))
        drafts = jnp.full((B, S - 1), draft_tok, jnp.int32)
        zeros_f = jnp.zeros((B,), jnp.float32)
        tokens, n_acc, _, _, _ = spec_verify_sample(
            logits, drafts, jnp.zeros((B,), jnp.int32),
            jax.random.key(123), jnp.full((B,), -1, jnp.int32),
            jnp.ones((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
            jnp.ones((B,), jnp.float32), zeros_f, zeros_f,
            jnp.zeros((B, V), jnp.int32), with_top=jnp.asarray(False))
        first = np.asarray(tokens[:, 0])
        counts = np.bincount(first, minlength=V).astype(np.float64)
        expected = target * B
        chi2 = float(((counts - expected) ** 2 / expected).sum())
        assert chi2 < 60.0, (chi2, counts, expected)
        # acceptance rate must track p(draft): binomial 4-sigma band
        p_d = float(target[draft_tok])
        acc = float(np.asarray(n_acc).mean())
        sigma = (p_d * (1 - p_d) / B) ** 0.5
        assert abs(acc - p_d) < 4 * sigma, (acc, p_d)

    def test_greedy_rows_exact_match_rule(self):
        """Greedy rows accept iff draft == argmax; the emitted token is the
        argmax either way; the bonus is the last position's argmax."""
        from kubernetes_gpu_cluster_tpu.ops.sampling import spec_verify_sample
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        B, S, V = 4, 3, 32
        logits = jnp.asarray(rng.standard_normal((B, S, V)), jnp.float32)
        am = np.asarray(jnp.argmax(logits, axis=-1))          # [B, S]
        # row 0: both drafts right; row 1: first wrong; row 2: second
        # wrong; row 3: both wrong.
        drafts = np.stack([
            [am[0, 0], am[0, 1]],
            [(am[1, 0] + 1) % V, am[1, 1]],
            [am[2, 0], (am[2, 1] + 1) % V],
            [(am[3, 0] + 1) % V, (am[3, 1] + 1) % V]]).astype(np.int32)
        zeros_f = jnp.zeros((B,), jnp.float32)
        tokens, n_acc, _, _, _ = spec_verify_sample(
            logits, jnp.asarray(drafts), jnp.zeros((B,), jnp.int32),
            jax.random.key(0), jnp.full((B,), -1, jnp.int32),
            zeros_f, jnp.zeros((B,), jnp.int32), jnp.ones((B,), jnp.float32),
            zeros_f, zeros_f, jnp.zeros((B, V), jnp.int32),
            with_top=jnp.asarray(False))
        tokens = np.asarray(tokens)
        assert list(np.asarray(n_acc)) == [2, 0, 1, 0]
        # emitted tokens are the argmax chain up to accepted+1
        np.testing.assert_array_equal(tokens[0], am[0])       # all + bonus
        assert tokens[1, 0] == am[1, 0]
        assert tokens[2, 0] == am[2, 0] and tokens[2, 1] == am[2, 1]
        assert tokens[3, 0] == am[3, 0]


class TestSampledEngineRuns:
    def test_seeded_sampled_reproducible_with_spec(self):
        sp = SamplingParams(max_tokens=12, temperature=0.9, seed=5)
        a = make_engine(True).generate([list(REPETITIVE)], sp)[0]
        b = make_engine(True).generate([list(REPETITIVE)], sp)[0]
        assert a.output_token_ids == b.output_token_ids

    def test_sampled_with_penalties_and_filters_runs(self):
        sp = SamplingParams(max_tokens=12, temperature=0.8, seed=3,
                            top_k=20, top_p=0.9, frequency_penalty=1.0,
                            presence_penalty=0.5)
        out = make_engine(True).generate([list(REPETITIVE)], sp)[0]
        assert len(out.output_token_ids) == 12

    def test_forced_logit_bias_through_spec(self):
        sp = SamplingParams(max_tokens=6, temperature=0.0,
                            logit_bias={7: 100.0})
        out = make_engine(True).generate([list(REPETITIVE)], sp)[0]
        assert out.output_token_ids == [7] * 6


class TestObservability:
    def test_spec_metrics_and_trace(self):
        from kubernetes_gpu_cluster_tpu.serving.metrics import Metrics

        eng = make_engine(True)
        metrics = Metrics(eng)
        eng.generate([list(REPETITIVE)],
                     SamplingParams(max_tokens=24, temperature=0.0))
        assert eng.obs.step_kind_counts["spec"] > 0
        text = metrics.render()
        assert "kgct_spec_drafted_tokens_total" in text
        assert "kgct_spec_accepted_tokens_total" in text
        assert "kgct_spec_acceptance_ratio" in text
        ratio = eng.obs.spec_acceptance_ratio()
        assert ratio is not None and 0.0 < ratio <= 1.0
        kinds = [e.kind for e in eng.obs.tracer.events()]
        assert "spec" in kinds
        ev = next(e for e in eng.obs.tracer.events() if e.kind == "spec")
        assert ev.args["drafted"] > 0 and "accepted" in ev.args

    def test_fresh_engine_renders_no_ratio(self):
        """A spec-enabled engine that never ran a spec step must render the
        counters at 0 and NO acceptance-ratio gauge (nan-free /metrics)."""
        eng = make_engine(True)
        lines = eng.obs.render_prometheus()
        text = "\n".join(lines)
        assert "kgct_spec_drafted_tokens_total 0" in text
        assert "kgct_spec_acceptance_ratio " not in text


class TestInterop:
    def test_spec_with_mixed_batching_prefills_never_drafted(self):
        """Spec + mixed batching coexist: prefill work schedules ahead of
        spec (chunked prefill rows are never drafted), spec engages on the
        pure-decode steps, and greedy output stays byte-identical."""
        def engine(spec):
            cfg = EngineConfig(
                model=_MODEL, cache=CacheConfig(page_size=8, num_pages=128),
                scheduler=SchedulerConfig(
                    max_num_seqs=4, max_prefill_tokens=32,
                    decode_buckets=(1, 2, 4),
                    prefill_buckets=(32, 64, 128, 256),
                    mixed_batch_enabled=True,
                    spec_decode_enabled=spec, num_speculative_tokens=3))
            return LLMEngine(cfg, params=_PARAMS)

        sp = SamplingParams(max_tokens=12, temperature=0.0)
        # long repetitive prompt chunks; short one rides behind
        prompts = [REPETITIVE * 3, list(REPETITIVE)]
        ref = [o.output_token_ids for o in engine(False).generate(prompts, sp)]
        eng = engine(True)
        got = [o.output_token_ids for o in eng.generate(prompts, sp)]
        assert got == ref
        assert eng.obs.step_kind_counts["spec"] > 0

    @pytest.mark.skipif(not hasattr(jax, "shard_map"),
                        reason="env gap: jax.shard_map missing (building a "
                               "pp-mesh engine needs it); same gate as the "
                               "other pp tests")
    def test_spec_disabled_under_pp_mesh_config(self):
        """pp/sp meshes have no spec forward path: the engine must clear
        the flag instead of crashing in the first step (mirrors the mixed
        path's gating)."""
        from kubernetes_gpu_cluster_tpu.parallel import mesh_from_config
        from kubernetes_gpu_cluster_tpu.config import ParallelConfig

        mesh = mesh_from_config(ParallelConfig(pp=2))
        cfg = EngineConfig(
            model=_MODEL.replace(num_layers=2),
            cache=CacheConfig(page_size=8, num_pages=64),
            scheduler=SchedulerConfig(
                max_num_seqs=2, max_prefill_tokens=64,
                decode_buckets=(1, 2), prefill_buckets=(64,),
                mixed_batch_enabled=False,
                spec_decode_enabled=True))
        eng = LLMEngine(cfg, mesh=mesh)
        assert eng.scheduler.spec_enabled is False
        assert eng._spec_verify_fn is None
