"""Interop tests for the C++ TPU device plugin (cluster/device-plugin).

The plugin embeds its own gRPC/HTTP2/HPACK/protobuf stack (no deps), so these
tests are wire-level interop proofs against PRODUCTION implementations:

- the fake kubelet is a real grpcio server + protoc-generated v1beta1
  messages: the plugin's Registration CLIENT must speak real gRPC to it;
- the DevicePlugin service is driven by a real grpcio CLIENT: ListAndWatch /
  Allocate / GetDevicePluginOptions responses must parse with libprotobuf.

Covers the reference's device-plugin layer (reference README.md:90,
old_README.md:1206-1318 — registration log signatures and allocation checks)
as automated tests instead of runbook transcripts.
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

REPO = pathlib.Path(__file__).resolve().parent.parent
PLUGIN_DIR = REPO / "cluster" / "device-plugin"


# -- build + protoc fixtures -------------------------------------------------

@pytest.fixture(scope="module")
def plugin_bin():
    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    r = subprocess.run(["make", "-C", str(PLUGIN_DIR)], capture_output=True,
                       text=True)
    assert r.returncode == 0, f"device plugin build failed:\n{r.stderr}"
    return PLUGIN_DIR / "build" / "kgct-tpu-device-plugin"


@pytest.fixture(scope="module")
def pb():
    if shutil.which("protoc") is None:
        pytest.skip("no protoc")
    out = tempfile.mkdtemp(prefix="kgct-proto-")
    r = subprocess.run(
        ["protoc", f"--python_out={out}", "v1beta1.proto"],
        cwd=PLUGIN_DIR / "proto", capture_output=True, text=True)
    assert r.returncode == 0, f"protoc failed:\n{r.stderr}"
    sys.path.insert(0, out)
    try:
        import v1beta1_pb2  # noqa: E402
        yield v1beta1_pb2
    finally:
        sys.path.remove(out)


class FakeKubelet:
    """grpcio server on <dir>/kubelet.sock implementing v1beta1.Registration."""

    def __init__(self, pb, plugin_dir: str):
        self.pb = pb
        self.requests: list = []
        self.event = threading.Event()
        handler = grpc.method_handlers_generic_handler(
            "v1beta1.Registration",
            {"Register": grpc.unary_unary_rpc_method_handler(
                self._register,
                request_deserializer=pb.RegisterRequest.FromString,
                response_serializer=pb.Empty.SerializeToString)})
        self.server = grpc.server(
            __import__("concurrent.futures", fromlist=["ThreadPoolExecutor"])
            .ThreadPoolExecutor(max_workers=2))
        self.server.add_generic_rpc_handlers((handler,))
        self.server.add_insecure_port(f"unix://{plugin_dir}/kubelet.sock")
        self.server.start()

    def _register(self, request, context):
        self.requests.append(request)
        self.event.set()
        return self.pb.Empty()

    def stop(self):
        self.server.stop(0)


@pytest.fixture()
def harness(plugin_bin, pb, tmp_path):
    """Fake devices + fake kubelet + running plugin; yields (pb, dirs, proc)."""
    devdir = tmp_path / "dev"
    devdir.mkdir()
    for i in range(4):
        (devdir / f"accel{i}").touch()
    plugdir = tmp_path / "plugins"
    plugdir.mkdir()

    kubelet = FakeKubelet(pb, str(plugdir))
    # stderr goes to a file, not a PIPE: reading a PIPE from a still-running
    # process blocks forever (and an undrained PIPE would wedge the plugin
    # after 64KB of logs).
    errpath = tmp_path / "plugin.stderr"
    with open(errpath, "w") as errf:
        proc = subprocess.Popen(
            [str(plugin_bin), f"--plugin-dir={plugdir}",
             f"--dev-root={devdir}", "--health-interval-s=1"],
            stderr=errf, text=True)
    proc.errpath = errpath
    try:
        yield pb, devdir, plugdir, kubelet, proc
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        kubelet.stop()


def _channel(plugdir):
    ch = grpc.insecure_channel(f"unix://{plugdir}/kgct-tpu.sock")
    grpc.channel_ready_future(ch).result(timeout=10)
    return ch


# -- tests -------------------------------------------------------------------

def test_registers_with_kubelet(harness):
    pb, _, _, kubelet, proc = harness
    assert kubelet.event.wait(timeout=15), (
        "plugin did not register; stderr:\n" + proc.errpath.read_text())
    req = kubelet.requests[0]
    assert req.version == "v1beta1"
    assert req.endpoint == "kgct-tpu.sock"
    assert req.resource_name == "google.com/tpu"
    assert not req.options.pre_start_required


def test_list_and_watch_and_allocate(harness):
    pb, devdir, plugdir, kubelet, proc = harness
    assert kubelet.event.wait(timeout=15)
    ch = _channel(plugdir)

    # GetDevicePluginOptions (unary, empty-message round trip).
    opts = ch.unary_unary(
        "/v1beta1.DevicePlugin/GetDevicePluginOptions",
        request_serializer=pb.Empty.SerializeToString,
        response_deserializer=pb.DevicePluginOptions.FromString,
    )(pb.Empty(), timeout=10)
    assert not opts.get_preferred_allocation_available

    # ListAndWatch: first streamed inventory.
    stream = ch.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch",
        request_serializer=pb.Empty.SerializeToString,
        response_deserializer=pb.ListAndWatchResponse.FromString,
    )(pb.Empty(), timeout=30)
    first = next(iter(stream))
    ids = sorted(d.ID for d in first.devices)
    assert ids == ["accel0", "accel1", "accel2", "accel3"]
    assert all(d.health == "Healthy" for d in first.devices)

    # Allocate two chips: device specs + TPU_VISIBLE_CHIPS env.
    req = pb.AllocateRequest()
    creq = req.container_requests.add()
    creq.devicesIDs.extend(["accel1", "accel3"])
    resp = ch.unary_unary(
        "/v1beta1.DevicePlugin/Allocate",
        request_serializer=pb.AllocateRequest.SerializeToString,
        response_deserializer=pb.AllocateResponse.FromString,
    )(req, timeout=10)
    assert len(resp.container_responses) == 1
    cr = resp.container_responses[0]
    assert {d.host_path for d in cr.devices} == {
        f"{devdir}/accel1", f"{devdir}/accel3"}
    assert {d.container_path for d in cr.devices} == {
        "/dev/accel1", "/dev/accel3"}
    assert all(d.permissions == "rw" for d in cr.devices)
    assert cr.envs["TPU_VISIBLE_CHIPS"] == "1,3"
    ch.close()


def test_health_change_pushes_update(harness):
    pb, devdir, plugdir, kubelet, proc = harness
    assert kubelet.event.wait(timeout=15)
    ch = _channel(plugdir)
    stream = ch.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch",
        request_serializer=pb.Empty.SerializeToString,
        response_deserializer=pb.ListAndWatchResponse.FromString,
    )(pb.Empty(), timeout=30)
    it = iter(stream)
    first = next(it)
    assert len(first.devices) == 4

    (devdir / "accel2").unlink()          # chip disappears
    second = next(it)                     # pushed within health-interval (1s)
    ids = sorted(d.ID for d in second.devices)
    assert ids == ["accel0", "accel1", "accel3"]
    ch.close()


def test_allocate_unknown_device_fails(harness):
    pb, _, plugdir, kubelet, proc = harness
    assert kubelet.event.wait(timeout=15)
    ch = _channel(plugdir)
    req = pb.AllocateRequest()
    req.container_requests.add().devicesIDs.append("accel9")
    with pytest.raises(grpc.RpcError) as e:
        ch.unary_unary(
            "/v1beta1.DevicePlugin/Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )(req, timeout=10)
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
    ch.close()


def test_write_cdi_spec(plugin_bin, tmp_path):
    """--write-cdi-spec emits a valid CDI json for the chips (C19 parity:
    the reference generated /etc/cdi/nvidia.yaml via nvidia-ctk,
    gpu-crio-setup.sh:87-101)."""
    import json
    devdir = tmp_path / "dev"
    devdir.mkdir()
    for i in range(2):
        (devdir / f"accel{i}").touch()
    spec_path = tmp_path / "kgct-tpu.json"
    r = subprocess.run(
        [str(plugin_bin), f"--dev-root={devdir}",
         f"--write-cdi-spec={spec_path}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    spec = json.loads(spec_path.read_text())
    assert spec["kind"] == "google.com/tpu"
    assert [d["name"] for d in spec["devices"]] == ["0", "1"]
    nodes = spec["devices"][1]["containerEdits"]["deviceNodes"][0]
    assert nodes["path"] == "/dev/accel1"
    assert nodes["hostPath"] == f"{devdir}/accel1"
