"""Compile-count guard: bucketed shapes keep the XLA jit cache bounded.

The engine's whole recompilation-storm defense is shape bucketing: every
step program compiles once per (kind, padded bucket) and is reused for the
serving lifetime. The mixed prefill/decode path adds a new shape family —
(prefill bucket, sampled-row bucket, history-table width) — so this guard
simulates a mixed load (staggered arrivals, varied prompt lengths, chunked
long prompts, mixing on) and asserts:

1. the total number of compiled step-program variants stays under a fixed
   bound derived from the bucket grid (a per-context-length or per-batch
   recompile would blow through it immediately), and
2. a second identical load wave compiles NOTHING new — steady state means
   zero compiles, which is the property sustained serving depends on.

Tier-1 (not slow): a shape-bucket regression must fail fast.
"""

import numpy as np

from kubernetes_gpu_cluster_tpu.config import (CacheConfig, EngineConfig,
                                               SchedulerConfig,
                                               get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

PREFILL_BUCKETS = (16, 32)
DECODE_BUCKETS = (1, 2, 4)


def _engine():
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=129),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=32,
            decode_buckets=DECODE_BUCKETS, prefill_buckets=PREFILL_BUCKETS,
            decode_window=2, mixed_batch_enabled=True))
    return LLMEngine(cfg)


def _compiled_variants(eng) -> int:
    """Total jit-cache entries across every step program — the number of
    distinct XLA compilations the load has triggered. Includes the two-tier
    KV cache's swap gather/scatter programs when the host tier is on. The
    ONE definition lives on the engine (it also feeds the
    ``kgct_jit_compiles_total`` gauge), so the guard and the metric cannot
    drift — but the guard pins it is actually counting something by
    cross-checking one raw jit cache."""
    total = eng.compiled_step_variants()
    if hasattr(eng._prefill_fn, "_cache_size"):
        assert total >= eng._prefill_fn._cache_size()
    return total


def _run_wave(eng, tag: str) -> None:
    """Staggered mixed load: varied prompt lengths (sub-bucket, bucket-edge,
    chunked-long), arrivals interleaved with steps so prefills land while
    decodes run (the mixed path) and also while idle (the pure path)."""
    rng = np.random.default_rng(0)
    lengths = [5, 16, 33, 60, 90, 12]
    params = SamplingParams(max_tokens=4, temperature=0.0)
    pending = [(f"{tag}-{i}", rng.integers(1, 500, n).tolist())
               for i, n in enumerate(lengths)]
    while pending or eng.has_unfinished_requests():
        if pending:
            rid, prompt = pending.pop(0)
            eng.add_request(rid, prompt, params)
        for _ in range(2):
            if eng.has_unfinished_requests():
                eng.step()
    while eng.has_unfinished_requests():
        eng.step()


def test_mixed_load_compile_count_bounded():
    eng = _engine()
    _run_wave(eng, "w1")
    first = _compiled_variants(eng)
    assert eng.obs.step_kind_counts["mixed"] > 0, \
        "simulation never exercised the mixed path"
    # Bound from the bucket grid: prefill (Tp x rows), mixed (Tp x rows x
    # history widths — pages for <=90-token prompts at ps=8 span 3 pow-2
    # widths), solo-chunk (Tp x widths), decode (batch buckets x 2 modes).
    n_tp, n_rows = len(PREFILL_BUCKETS), len(DECODE_BUCKETS)
    bound = (n_tp * n_rows          # pure prefill
             + n_tp * n_rows * 3    # mixed
             + n_tp * 3             # solo chunk
             + n_rows * 2)          # decode greedy/sampled
    assert 0 < first <= bound, (first, bound)

    # Steady state: an identical second wave must reuse every compiled
    # variant — one new shape here means some step input scales with
    # context/batch instead of a bucket.
    _run_wave(eng, "w2")
    assert _compiled_variants(eng) == first, \
        "second identical load wave triggered new XLA compilations"


def _spec_engine(k: int = 3):
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=129),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=32,
            decode_buckets=DECODE_BUCKETS, prefill_buckets=PREFILL_BUCKETS,
            decode_window=2, mixed_batch_enabled=True,
            spec_decode_enabled=True, num_speculative_tokens=k))
    return LLMEngine(cfg)


def _run_spec_wave(eng, tag: str) -> None:
    """Mixed spec load: repetitive prompts (n-gram drafts hit, spec steps
    fire at several row buckets) plus structureless ones (spec bows out to
    legacy decode), staggered so prefill/mixed/spec/decode all occur."""
    rng = np.random.default_rng(1)
    pattern = rng.integers(1, 500, 4).tolist()
    prompts = [pattern * 4, rng.integers(1, 500, 12).tolist(),
               pattern * 7, pattern * 2, rng.integers(1, 500, 30).tolist()]
    params = SamplingParams(max_tokens=8, temperature=0.0)
    pending = [(f"{tag}-{i}", list(p)) for i, p in enumerate(prompts)]
    while pending or eng.has_unfinished_requests():
        if pending:
            rid, prompt = pending.pop(0)
            eng.add_request(rid, prompt, params)
        for _ in range(3):
            if eng.has_unfinished_requests():
                eng.step()
    while eng.has_unfinished_requests():
        eng.step()


def test_spec_load_compile_count_bounded():
    """Spec-decode steps stay inside the bucket-grid compile bound: the
    verify program's token width is R_pad * (k+1) with k STATIC config, so
    it adds at most one variant per decode bucket — and a second identical
    spec wave compiles NOTHING new."""
    eng = _spec_engine()
    _run_spec_wave(eng, "w1")
    assert eng.obs.step_kind_counts["spec"] > 0, \
        "simulation never exercised a spec-verify step"
    first = _compiled_variants(eng)
    n_tp, n_rows = len(PREFILL_BUCKETS), len(DECODE_BUCKETS)
    bound = (n_tp * n_rows          # pure prefill
             + n_tp * n_rows * 3    # mixed
             + n_tp * 3             # solo chunk
             + n_rows * 2           # decode greedy/sampled
             + n_rows)              # spec verify: one per row bucket
    assert 0 < first <= bound, (first, bound)

    _run_spec_wave(eng, "w2")
    assert _compiled_variants(eng) == first, \
        "second identical spec wave triggered new XLA compilations"


def _spec_draft_engine(k: int = 3):
    """Draft-model + adaptive-k + mixed: the full composition — the
    spec×mixed program family, the draft model's own decode/prefill
    families, and the adaptive ladder's per-k variants all ride one
    engine."""
    from kubernetes_gpu_cluster_tpu.models import llama as model_lib
    import jax

    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=129),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=32,
            decode_buckets=DECODE_BUCKETS, prefill_buckets=PREFILL_BUCKETS,
            decode_window=2, mixed_batch_enabled=True,
            spec_decode_enabled=True, num_speculative_tokens=k,
            spec_draft_model="debug-tiny"))
    params = model_lib.init_params(cfg.model, jax.random.key(0))
    # Oracle draft (same params): every draft accepts, so spec and
    # spec_mixed steps fire deterministically at several row buckets.
    return LLMEngine(cfg, params=params, draft_params=params)


def _run_spec_mixed_wave(eng, tag: str) -> None:
    """Composition wave: a long-lived repetitive session keeps verify
    slices live (the oracle draft always proposes) while a
    longer-than-budget prompt chunks and short prompts arrive — chunk +
    verify slices must share dispatched steps, at more than one row
    bucket."""
    rng = np.random.default_rng(2)
    pattern = rng.integers(1, 500, 4).tolist()
    sess = SamplingParams(max_tokens=30, temperature=0.0)
    short = SamplingParams(max_tokens=6, temperature=0.0)
    eng.add_request(f"{tag}-s0", pattern * 5, sess)
    for _ in range(3):
        eng.step()
    eng.add_request(f"{tag}-s1", pattern * 3, sess)
    for _ in range(2):
        eng.step()
    eng.add_request(f"{tag}-long", pattern * 12, short)   # 48 > 32: chunks
    eng.add_request(f"{tag}-p", rng.integers(1, 500, 12).tolist(), short)
    while eng.has_unfinished_requests():
        eng.step()


def test_spec_mixed_draft_load_compile_count_bounded():
    """The composition's compile families stay bounded and steady-state:
    spec×mixed adds (prefill bucket x row bucket x history width) per
    ladder rung, the draft model adds its decode-per-row-bucket and
    chunked-prefill families — and a second identical wave compiles
    NOTHING new (the zero-new-compiles bar sustained serving depends on),
    counted through the same engine seam the kgct_jit_compiles_total
    gauge reads, draft programs included."""
    eng = _spec_draft_engine()
    _run_spec_mixed_wave(eng, "w1")
    assert eng.obs.step_kind_counts["spec"] > 0
    assert eng.obs.step_kind_counts["spec_mixed"] > 0, \
        "simulation never composed a chunk with verify slices"
    first = _compiled_variants(eng)
    n_tp, n_rows = len(PREFILL_BUCKETS), len(DECODE_BUCKETS)
    bound = (n_tp * n_rows          # pure prefill
             + n_tp * n_rows * 3    # mixed
             + n_tp * 3             # solo chunk
             + n_rows * 2           # decode greedy/sampled
             + n_rows               # spec verify: one per row bucket
             + n_tp * n_rows * 3    # spec_mixed: (Tp x rows x widths)
             + n_rows               # draft decode: one per row bucket
             + 12)                  # draft chunked prefill (T x width grid)
    assert 0 < first <= bound, (first, bound)

    _run_spec_mixed_wave(eng, "w2")
    assert _compiled_variants(eng) == first, \
        "second identical spec×mixed/draft wave triggered new compilations"


def _swap_engine():
    """Page-starved pool + host tier: decode growth must preempt-by-swap
    (and restore) during the wave, exercising the gather/scatter programs."""
    # Mixing off: the swap path preempts inside _grow_decode_pages either
    # way, and skipping the mixed program's compiles keeps this guard cheap
    # (the mixed family's bound is test_mixed_load_compile_count_bounded).
    cfg = EngineConfig(
        model=get_model_config("debug-tiny"),
        cache=CacheConfig(page_size=8, num_pages=13, swap_space_gb=0.01),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_prefill_tokens=32,
            decode_buckets=DECODE_BUCKETS, prefill_buckets=PREFILL_BUCKETS,
            decode_window=2, mixed_batch_enabled=False))
    return LLMEngine(cfg)


def _run_swap_wave(eng, tag: str) -> None:
    rng = np.random.default_rng(3)
    lengths = [12, 16, 10, 14]
    params = SamplingParams(max_tokens=12, temperature=0.0)
    for i, n in enumerate(lengths):
        eng.add_request(f"{tag}-{i}", rng.integers(1, 500, n).tolist(),
                        params)
    while eng.has_unfinished_requests():
        eng.step()


def test_swap_load_compile_count_bounded():
    """Swap gather/scatter add a BOUNDED compile family: page-count inputs
    pad to powers of two, so each direction compiles at most
    log2(max pages/seq)+1 variants — and a second identical swap wave
    compiles NOTHING new (steady-state serving never recompiles for swap)."""
    from kubernetes_gpu_cluster_tpu.utils.math import next_power_of_2

    eng = _swap_engine()
    _run_swap_wave(eng, "w1")
    assert eng.scheduler.num_preemptions_by_kind["swap"] > 0, \
        "simulation never exercised a swap preemption"
    assert eng.obs.swap_pages["in"] > 0, "no swapped sequence was restored"
    first = _compiled_variants(eng)
    n_tp, n_rows = len(PREFILL_BUCKETS), len(DECODE_BUCKETS)
    max_pages = eng.config.effective_max_len // 8
    n_swap_sizes = int(np.log2(next_power_of_2(max_pages))) + 1
    bound = (n_tp * n_rows          # pure prefill
             + n_tp * n_rows * 3    # mixed
             + n_tp * 3             # solo chunk
             + n_rows * 2           # decode greedy/sampled
             + 2 * n_swap_sizes)    # swap gather + scatter, pow-2 sizes
    assert 0 < first <= bound, (first, bound)

    _run_swap_wave(eng, "w2")
    assert _compiled_variants(eng) == first, \
        "second identical swap wave triggered new XLA compilations"
