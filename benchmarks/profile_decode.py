"""Decode-step component profile on the current backend (run on the real chip).

Methodology for tunnel-attached TPUs: the host<->device round trip is ~110 ms
and result downloads are slow, so each measurement CHAINS the op N times
device-side (python-level feedback of on-device buffers, async dispatch) and
fetches ONE scalar at the end; per-iteration time = (total - latency) / N.

Components timed at the serving bench shape (TinyLlama-1.1B, B=64):
  1. one decode substep (forward + logits), XLA vs Pallas attention
  2. weights-only pass (attention stubbed) - the HBM weight-streaming floor
  3. the attention op alone (both paths), one layer x L
  4. KV scatter (write_kv_pages_all) alone
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_gpu_cluster_tpu.config import CacheConfig, get_model_config
from kubernetes_gpu_cluster_tpu.engine.kv_cache import allocate_kv_cache
from kubernetes_gpu_cluster_tpu.models import llama as model_lib
from kubernetes_gpu_cluster_tpu.ops import attention as attn

B = 64
CTX = 320            # mid-stream context (prompt 128 + ~192 decoded)
PS = 16
MODEL = "tinyllama-1.1b" if jax.default_backend() == "tpu" else "debug-tiny"
CHAIN = 30


def sync(x):
    leaf = jax.tree.leaves(x)[0]
    return np.asarray(leaf.ravel()[0])


def timed_chain(fn, state, chain=CHAIN):
    """fn(state) -> state (device buffers; fn may donate its input). Chains
    ``chain`` calls, one scalar fetch at the end. Returns per-call ms with the
    host round-trip latency subtracted."""
    s = fn(state)                 # warmup / compile (may donate `state`)
    sync(s)
    t0 = time.perf_counter()
    sync(s)
    latency = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(chain):
        s = fn(s)
    sync(s)
    total = time.perf_counter() - t0
    return max(total - latency, 0.0) / chain * 1e3


def main():
    cfg = get_model_config(MODEL)
    nkv, hd, nh, L = cfg.num_kv_heads, cfg.head_dim, cfg.num_heads, cfg.num_layers
    pages_per_seq = cfg.max_model_len // PS
    num_pages = B * (CTX // PS + 2) + 1
    cache_cfg = CacheConfig(page_size=PS, num_pages=num_pages)

    def mk_kv():
        # Fresh pool per measurement: the substep chains DONATE the pool, so
        # a shared one would be invalidated after the first measurement.
        return allocate_kv_cache(cfg, cache_cfg, num_pages)

    kv = mk_kv()
    params = model_lib.init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(0)
    pt = np.zeros((B, pages_per_seq), np.int32)
    used = CTX // PS + 1
    perm = rng.permutation(np.arange(1, num_pages))[: B * used].reshape(B, used)
    pt[:, :used] = perm
    page_tables = jnp.asarray(pt)
    positions = jnp.full((B,), CTX - 1, jnp.int32)
    context_lens = jnp.full((B,), CTX, jnp.int32)
    slot_mapping = jnp.asarray(perm[:, (CTX - 1) // PS] * PS + (CTX - 1) % PS)
    tokens0 = jnp.asarray(rng.integers(1, cfg.vocab_size, B).astype(np.int32))
    meta = model_lib.DecodeMeta(positions=positions, slot_mapping=slot_mapping,
                                page_tables=page_tables, context_lens=context_lens)

    kv_bytes = 2 * kv.k.size * kv.k.dtype.itemsize
    par_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    print(f"model={MODEL} L={L} nh={nh} nkv={nkv} hd={hd} B={B} ctx={CTX} "
          f"pages/seq={pages_per_seq}")
    print(f"params={par_bytes/1e9:.2f} GB, kv pool={kv_bytes/1e9:.2f} GB, "
          f"backend={jax.default_backend()}")

    # --- 1+2: decode substep (greedy-sample feedback keeps it on device) ----
    # params flow through state as a jit ARGUMENT: closing over them would
    # bake 2.2 GB of weights into the program as constants — each compile
    # then re-uploads the model through the tunnel (minutes per measurement).
    def substep(use_pallas, stub=False):
        @functools.partial(jax.jit, donate_argnums=1)
        def f(prms, kvc, tokens):
            real = attn.paged_decode_attention
            if stub:   # trace-time stub; restored right after tracing
                attn.paged_decode_attention = lambda q, *a, **k: q
            try:
                hidden, kvc, _ = model_lib.forward_decode(
                    prms, cfg, tokens, meta, kvc, use_pallas=use_pallas)
            finally:
                attn.paged_decode_attention = real
            logits = model_lib.compute_logits(prms, cfg, hidden)
            return kvc, jnp.argmax(logits, -1).astype(jnp.int32)

        return lambda state: f(params, *state)   # params: argument, not donated

    print(f"substep XLA attn:      {timed_chain(substep(False), (mk_kv(), tokens0)):8.3f} ms")
    if jax.default_backend() == "tpu":
        print(f"substep Pallas attn:   {timed_chain(substep(True), (mk_kv(), tokens0)):8.3f} ms")
    print(f"substep attn-stub:     {timed_chain(substep(False, stub=True), (mk_kv(), tokens0)):8.3f} ms")

    # --- 3: attention alone, scanned over L layers --------------------------
    q1 = jnp.asarray(rng.standard_normal((B, nh, hd)), cfg.jnp_dtype)
    kc = jnp.asarray(rng.standard_normal((B, nkv, hd)), cfg.jnp_dtype)
    vc = jnp.asarray(rng.standard_normal((B, nkv, hd)), cfg.jnp_dtype)

    def attn_loop(use_pallas):
        @jax.jit
        def f(q1, k_pool, v_pool):
            def body(acc, xs):
                kp, vp = xs
                o = attn.paged_decode_attention(
                    q1, kp, vp, page_tables, context_lens, kc, vc,
                    hd ** -0.5, use_pallas=use_pallas)
                return acc + o.astype(jnp.float32), None
            acc, _ = jax.lax.scan(body, jnp.zeros((B, nh, hd), jnp.float32),
                                  (k_pool, v_pool))
            return acc.astype(cfg.jnp_dtype)
        # pool passed as argument (a closed-over pool would be baked into the
        # program as 0.5 GB of constants and re-uploaded at compile)
        def step(state):
            out = f(state[0], kv.k, kv.v)
            return (out, None)
        return step

    print(f"attn x{L} XLA:          {timed_chain(attn_loop(False), (q1, None)):8.3f} ms")
    if jax.default_backend() == "tpu":
        print(f"attn x{L} Pallas:       {timed_chain(attn_loop(True), (q1, None)):8.3f} ms")

    # --- 4: KV scatter alone ------------------------------------------------
    k_all = jnp.asarray(rng.standard_normal((L, B, nkv, hd)), cfg.jnp_dtype)

    @functools.partial(jax.jit, donate_argnums=0)
    def scatter(state):
        kvc, t = state
        return attn.write_kv_pages_all(kvc[0], kvc[1], k_all, k_all,
                                       slot_mapping), t

    kv_s = mk_kv()
    print(f"kv scatter:            {timed_chain(scatter, ((kv_s.k, kv_s.v), tokens0)):8.3f} ms")


if __name__ == "__main__":
    main()
