"""Compile + numerics check for the Pallas kernels ON THE REAL TPU CHIP.

Round-2 postmortem: interpret-mode tests cannot catch Mosaic compile errors
(VERDICT weak #3) — this script is the on-chip gate. Run it whenever a kernel
changes; bench.py and the engine's probe compile are the automated backstops.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_gpu_cluster_tpu.ops.attention import (
    paged_decode_attention_xla, ragged_prefill_attention_xla)
from kubernetes_gpu_cluster_tpu.ops.pallas.paged_decode import pallas_paged_decode
from kubernetes_gpu_cluster_tpu.ops.pallas.flash_prefill import flash_ragged_prefill


def check_decode() -> None:
    # TinyLlama-1.1B decode shapes: nh=32, n_kv=4, hd=64 -> kd=256.
    B, nh, n_kv, hd, ps, pps = 64, 32, 4, 64, 16, 52
    P = 2048
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, nh, hd)), jnp.bfloat16)
    k_pool = jnp.asarray(rng.standard_normal((P, ps, n_kv * hd)), jnp.bfloat16)
    v_pool = jnp.asarray(rng.standard_normal((P, ps, n_kv * hd)), jnp.bfloat16)
    # Distinct pages per sequence, padding entries -> scrap page 0.
    tables = np.zeros((B, pps), np.int32)
    ctx = rng.integers(2, pps * ps, B).astype(np.int32)
    ctx[0] = 1  # empty-pool path: n_chunks == 0, no DMA ever starts
    next_page = 1
    for b in range(B):
        n = -(-int(ctx[b] - 1) // ps)
        for j in range(n):
            tables[b, j] = next_page
            next_page += 1
    assert next_page <= P, f"pool too small: need {next_page} pages"
    tables = jnp.asarray(tables)
    ctx = jnp.asarray(ctx)
    k_cur = jnp.asarray(rng.standard_normal((B, n_kv, hd)), jnp.bfloat16)
    v_cur = jnp.asarray(rng.standard_normal((B, n_kv, hd)), jnp.bfloat16)
    scale = hd ** -0.5

    ref = paged_decode_attention_xla(q, k_pool, v_pool, tables, ctx,
                                     k_cur, v_cur, scale)
    fn = jax.jit(lambda *a: pallas_paged_decode(*a, scale))
    out = fn(q, k_pool, v_pool, tables, ctx, k_cur, v_cur)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) -
                                ref.astype(jnp.float32))))
    print(f"decode: max|pallas-xla| = {err:.4f}")
    assert err < 0.06, err


def check_prefill() -> None:
    T, nh, n_kv, hd = 512, 32, 4, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.bfloat16)
    # Three segments + trailing padding.
    seg = np.concatenate([np.full(200, 0), np.full(200, 1), np.full(80, 2),
                          np.full(32, -1)]).astype(np.int32)
    pos = np.concatenate([np.arange(200), np.arange(200), np.arange(80),
                          np.zeros(32)]).astype(np.int32)
    seg, pos = jnp.asarray(seg), jnp.asarray(pos)
    scale = hd ** -0.5
    ref = ragged_prefill_attention_xla(q, k, v, seg, pos, scale)
    fn = jax.jit(lambda *a: flash_ragged_prefill(*a, scale))
    out = fn(q, k, v, seg, pos)
    mask = np.asarray(seg) >= 0
    err = float(jnp.max(jnp.abs((out.astype(jnp.float32) -
                                 ref.astype(jnp.float32))[mask])))
    print(f"prefill: max|pallas-xla| = {err:.4f}")
    assert err < 0.06, err


def check_prefill_history() -> None:
    # TinyLlama geometry, 512-token chunk over 3.5 pages of history.
    from kubernetes_gpu_cluster_tpu.ops.attention import (
        prefill_history_attention_xla)
    from kubernetes_gpu_cluster_tpu.ops.pallas.flash_prefill_hist import (
        flash_prefill_history)

    T, nh, n_kv, hd, ps, pps, L = 512, 32, 4, 64, 128, 8, 2
    hist_len = 3 * ps + 70
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((T, nh, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((T, n_kv, hd)), jnp.bfloat16)
    pad = 32
    seg = jnp.asarray(np.where(np.arange(T) < T - pad, 0, -1), jnp.int32)
    pos = jnp.asarray(np.where(np.arange(T) < T - pad,
                               hist_len + np.arange(T), 0), jnp.int32)
    pool_k = jnp.asarray(rng.standard_normal((L, 1 + pps, ps, n_kv * hd)),
                         jnp.bfloat16)
    pool_v = jnp.asarray(rng.standard_normal((L, 1 + pps, ps, n_kv * hd)),
                         jnp.bfloat16)
    pt = jnp.asarray(1 + np.arange(pps), jnp.int32)
    hl = jnp.asarray(hist_len, jnp.int32)
    scale = hd ** -0.5
    layer = jnp.asarray(1, jnp.int32)

    ref = prefill_history_attention_xla(q, k, v, seg, pos, pool_k, pool_v,
                                        pt, hl, scale, layer=layer)
    fn = jax.jit(lambda *a: flash_prefill_history(*a, scale, layer=layer))
    out = fn(q, k, v, seg, pos, pool_k, pool_v, pt, hl)
    mask = np.asarray(seg) >= 0
    err = float(jnp.max(jnp.abs((out.astype(jnp.float32)
                                 - ref.astype(jnp.float32))[mask])))
    print(f"prefill_history: max|pallas-xla| = {err:.4f}")
    assert err < 0.06, err


def check_int4_matmul() -> None:
    """W4A16 dequant-fused matmul (ops/pallas/int4_matmul.py): packed tiles
    dequantized in VMEM vs the XLA fusion path, at an 8B-decode-like shape
    (B=64 rows, hidden 4096 -> ff 14336 column block)."""
    from kubernetes_gpu_cluster_tpu.ops.pallas.int4_matmul import (
        pallas_int4_matmul)
    from kubernetes_gpu_cluster_tpu.ops.quant import (int4_matmul_xla,
                                                      quantize_tensor_int4)

    T, K, N, gs = 64, 4096, 1024, 128
    rng = np.random.default_rng(3)
    w = rng.standard_normal((K, N)).astype(np.float32) * K ** -0.5
    x = jnp.asarray(rng.standard_normal((T, K)), jnp.bfloat16)
    packed, sc = quantize_tensor_int4(w, gs)
    packed, sc = jnp.asarray(packed), jnp.asarray(sc)
    ref = int4_matmul_xla(x, packed, sc)
    fn = jax.jit(lambda *a: pallas_int4_matmul(*a))
    out = fn(x, packed, sc)
    err = float(jnp.max(jnp.abs(out - ref)))
    print(f"int4_matmul: max|pallas-xla| = {err:.4f}")
    assert err < 0.06, err


if __name__ == "__main__":
    print("backend:", jax.default_backend())
    check_decode()
    check_prefill()
    check_prefill_history()
    check_int4_matmul()
    print("OK")
