#!/usr/bin/env bash
# Build (and optionally push) the two images the deploy surface references:
#   ghcr.io/kgct/tpu-serving:<TAG>       (deploy/render.py DEFAULT_IMAGE)
#   ghcr.io/kgct/tpu-device-plugin:<TAG> (device-plugin DaemonSet)
#
# Usage: docker/build.sh [--push] [--only serving|device-plugin]
#   REGISTRY=ghcr.io/kgct TAG=v0.3.0 docker/build.sh
#
# The tags default to exactly what the manifests/renderer reference, so a
# plain `docker/build.sh --push` makes the rendered deployment pullable.
set -euo pipefail

REGISTRY="${REGISTRY:-ghcr.io/kgct}"
TAG="${TAG:-v0.3.0}"
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PUSH=0
ONLY=""

while [[ $# -gt 0 ]]; do
  case "$1" in
    --push) PUSH=1; shift ;;
    --only) ONLY="$2"; shift 2 ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

# Quality gate BEFORE any image build: kgct-lint (empty baseline) + tier-1
# tests (scripts/check.sh) — an image can never ship lint-dirty code.
# KGCT_SKIP_CHECKS=1 is the explicit, logged escape hatch (e.g. building
# on a host without the test toolchain); KGCT_CHECK_ARGS="--lint-only"
# keeps the gate but skips the test run.
if [[ "${KGCT_SKIP_CHECKS:-0}" != 1 ]]; then
  # shellcheck disable=SC2086
  "${REPO_ROOT}/scripts/check.sh" ${KGCT_CHECK_ARGS:-}
else
  echo ">> WARNING: KGCT_SKIP_CHECKS=1 — building without lint/test gate" >&2
fi

build() {
  local name="$1" dockerfile="$2"
  local image="${REGISTRY}/${name}:${TAG}"
  echo ">> building ${image}"
  # TPU VMs are amd64 and the libtpu wheel set has no aarch64 build — pin the
  # platform so builds from arm64 hosts (Apple Silicon) produce a usable image.
  docker build --platform linux/amd64 \
    -f "${REPO_ROOT}/docker/${dockerfile}" -t "${image}" "${REPO_ROOT}"
  if [[ "${PUSH}" == 1 ]]; then
    echo ">> pushing ${image}"
    docker push "${image}"
  fi
}

[[ -z "${ONLY}" || "${ONLY}" == "serving" ]] && build tpu-serving Dockerfile.serving
[[ -z "${ONLY}" || "${ONLY}" == "device-plugin" ]] && build tpu-device-plugin Dockerfile.device-plugin
echo "done"
