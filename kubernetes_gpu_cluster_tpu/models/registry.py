"""Model registry: maps model names / HF ids to configs and forward fns.

All supported families share one decoder implementation (models/llama.py),
selected and specialized purely by ModelConfig — mirroring how the reference
selected models purely via the Helm ``modelURL`` string
(reference ``values-01-minimal-example3.yaml:8``)."""

from __future__ import annotations

from ..config.model_config import MODEL_PRESETS, ModelConfig, get_model_config  # noqa: F401


def list_models() -> list[str]:
    return sorted(MODEL_PRESETS)
