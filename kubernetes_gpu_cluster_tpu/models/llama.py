"""Decoder-only transformer for serving: llama-class dense + mixtral-class MoE.

One config-driven implementation covers every family the framework serves
(Llama 1/2/3, TinyLlama, Qwen2/2.5 [attention bias], Qwen3 [qk-norm],
Mixtral [sparse MoE]) — the model set the reference deployed through vLLM
images (reference ``values-01-minimal-example*.yaml`` modelURL fields) plus the
BASELINE.json north-star models.

TPU-first design decisions:
- Pure functions over a params pytree; layer weights are **stacked** with a
  leading ``[L, ...]`` axis and the layer loop is a ``lax.scan`` — one traced
  layer body regardless of depth (compile time O(1) in L), and the paged KV
  pool's ``[L, ...]`` leading axis threads through the scan as xs/ys.
- Two entry points matching the serving hot loop: ``forward_prefill`` (ragged
  flattened prompt tokens, causal-within-segment) and ``forward_decode`` (one
  token per sequence against the paged cache). Both scatter K/V into the page
  pool via precomputed slot mappings (padding slots land in the scrap page).
- Matmuls stay in model dtype (bf16) with fp32 accumulation on the MXU
  (``preferred_element_type``); norms/softmax in fp32.
- Only the hidden states that feed sampling are projected to logits
  (``logits_indices``), so the ``[*, vocab]`` matmul runs on B rows, not T.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..engine.kv_cache import KVCache
from ..ops import quant as quant_ops
from ..ops.rope import apply_rope, rope_cos_sin
from ..ops.attention import (
    write_kv_pages_all,
    ragged_prefill_attention,
    ragged_prefill_attention_tp,
    prefill_history_attention,
    prefill_history_attention_tp,
    paged_decode_attention,
    paged_decode_attention_tp,
    mixed_attention,
    spec_mixed_attention,
    spec_verify_attention,
)

Params = dict[str, Any]


class PrefillMeta(NamedTuple):
    """Metadata for a ragged prefill step over T flattened prompt tokens."""
    seg_ids: jax.Array        # [T] int32 sequence id per token; padding = -1
    positions: jax.Array      # [T] int32 position within its sequence
    slot_mapping: jax.Array   # [T] int32 flat KV slot (scrap page for padding)
    logits_indices: jax.Array # [B] int32 index into T of each seq's last token


class DecodeMeta(NamedTuple):
    """Metadata for a decode step: one new token per sequence."""
    positions: jax.Array      # [B] int32 position of the new token
    slot_mapping: jax.Array   # [B] int32 flat KV slot for the new token
    page_tables: jax.Array    # [B, pages_per_seq] int32 page ids (pad = scrap)
    context_lens: jax.Array   # [B] int32 valid tokens incl. the new one


class SpecMeta(NamedTuple):
    """Metadata for a speculative-verification step over one padded token
    axis ``T = R_pad * S``: every running sequence contributes S = k+1
    contiguous slots (its last committed token + k drafts), attending to
    its own paged-pool history plus the earlier slice tokens causally.
    The per-row slot count S is static per compiled shape
    (``S = T // page_tables.shape[0]``)."""
    seg_ids: jax.Array          # [T] int32: row id on real slots, -1 padding
    positions: jax.Array        # [T] int32 global positions (RoPE input)
    slot_mapping: jax.Array     # [T] int32 KV write slot (overflow -> scrap)
    page_tables: jax.Array      # [R_pad, pages_bucket] per-row history pages
    context_lens: jax.Array     # [R_pad] committed tokens incl. slot 0's


class MixedMeta(NamedTuple):
    """Metadata for a mixed step over one padded token axis
    ``T = Tp_bucket + R_pad``: a prefill chunk (tokens [0:Tp_bucket), one
    sequence, attending to its pool history) followed by decode rows
    (tokens [Tp_bucket:T), one per running sequence, against the paged
    pool). The split point is static per compiled shape:
    ``Tp_bucket = T - page_tables.shape[0]``."""
    seg_ids: jax.Array          # [T] int32: 0 on chunk tokens, -1 elsewhere
    positions: jax.Array        # [T] int32 global positions (RoPE)
    slot_mapping: jax.Array     # [T] int32 KV write slot (pad -> scrap page)
    logits_indices: jax.Array   # [R_pad] rows to sample: decode rows then
                                # the chunk's last token
    chunk_page_table: jax.Array # [1, hist_width] the chunk seq's pages
    hist_len: jax.Array         # [] int32 chunk history already in the pool
    page_tables: jax.Array      # [R_pad, pages_bucket] decode page tables
    context_lens: jax.Array     # [R_pad] decode valid tokens incl. current


# ---------------------------------------------------------------------------
# Parameter init
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array, dtype: Optional[jnp.dtype] = None) -> Params:
    """Random-init params (bench/tests; real weights come from engine.weights).
    Layout: stacked [L, ...] per-layer tensors + embed/final_norm/lm_head."""
    dtype = dtype or cfg.jnp_dtype

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    if cfg.quantization is not None:
        if cfg.quantization not in quant_ops.QUANT_METHODS:
            raise ValueError(
                f"unsupported quantization {cfg.quantization!r} "
                f"(one of {quant_ops.QUANT_METHODS})")
        return _init_params_quant(cfg, key, dtype, w)

    d, L = cfg.hidden_size, cfg.num_layers
    nh, nkv, hd, ff = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size
    E = cfg.num_experts
    keys = iter(jax.random.split(key, 16))

    layers: Params = {
        "input_norm": jnp.ones((L, d), dtype),
        "post_attn_norm": jnp.ones((L, d), dtype),
        "wq": w(next(keys), (L, d, nh * hd), d),
        "wk": w(next(keys), (L, d, nkv * hd), d),
        "wv": w(next(keys), (L, d, nkv * hd), d),
        "wo": w(next(keys), (L, nh * hd, d), nh * hd),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, nh * hd), dtype)
        layers["bk"] = jnp.zeros((L, nkv * hd), dtype)
        layers["bv"] = jnp.zeros((L, nkv * hd), dtype)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, hd), dtype)
        layers["k_norm"] = jnp.ones((L, hd), dtype)
    if cfg.is_moe:
        layers["router"] = w(next(keys), (L, d, E), d)
        layers["w_gate"] = w(next(keys), (L, E, d, ff), d)
        layers["w_up"] = w(next(keys), (L, E, d, ff), d)
        layers["w_down"] = w(next(keys), (L, E, ff, d), ff)
    else:
        if cfg.mlp_type != "mlp":
            layers["w_gate"] = w(next(keys), (L, d, ff), d)
        layers["w_up"] = w(next(keys), (L, d, ff), d)
        layers["w_down"] = w(next(keys), (L, ff, d), ff)
    _add_opt_extras(cfg, layers, dtype)

    params: Params = {
        "embed": w(next(keys), (cfg.vocab_size, d), d),
        "final_norm": jnp.ones((d,), dtype),
        "layers": layers,
    }
    if cfg.norm_type == "layernorm":
        params["final_norm_b"] = jnp.zeros((d,), dtype)
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = w(next(keys), (cfg.max_model_len + 2, d), d)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (d, cfg.vocab_size), d)
    return params


def _add_opt_extras(cfg: ModelConfig, layers: Params, dtype) -> None:
    """Per-layer OPT-class extras: LayerNorm biases and linear biases."""
    d, L, ff = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size
    if cfg.norm_type == "layernorm":
        layers["input_norm_b"] = jnp.zeros((L, d), dtype)
        layers["post_attn_norm_b"] = jnp.zeros((L, d), dtype)
    if cfg.linear_bias:
        layers["bo"] = jnp.zeros((L, d), dtype)
        layers["b_up"] = jnp.zeros((L, ff), dtype)
        layers["b_down"] = jnp.zeros((L, d), dtype)


def _init_params_quant(cfg: ModelConfig, key: jax.Array, dtype, w) -> Params:
    """Random-init directly in the quantized layout (same pytree structure
    as quantize_params output). Materializing the full bf16 model first and
    quantizing after — the naive path — peaks at 2x the bf16 footprint, which
    OOMs an 8B model on a 16 GB chip; random-init weights are synthetic
    anyway (bench/tests), so the big matmul weights are drawn in their
    quantized storage directly with a constant fan-in scale and nothing
    large ever exists in bf16. int4 draws the PACKED bytes (each holding
    two uniform nibbles), so the init's peak footprint is the packed
    half-size buffer. Real checkpoints quantize tensor-by-tensor at load
    (engine/weights.py)."""
    d, L = cfg.hidden_size, cfg.num_layers
    nh, nkv, hd, ff = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.intermediate_size
    E = cfg.num_experts
    gs = cfg.quant_group_size
    keys = iter(jax.random.split(key, 24))

    def wq8(key, shape, fan_in):
        if cfg.quantization == "int4":
            # Uniform random bytes = two uniform [-8, 7] nibbles each;
            # dequant std ~= 4.6 * scale ~= 0.66 * fan_in^-0.5 — same
            # magnitude class as the bf16 init, quality irrelevant for
            # random weights.
            din = shape[-2]
            if din % gs:
                raise ValueError(f"int4 input dim {din} not divisible by "
                                 f"quant_group_size {gs}")
            packed = jax.random.randint(
                key, shape[:-2] + (din // 2,) + shape[-1:], -128, 128,
                jnp.int8)
            scale = jnp.full(shape[:-2] + (din // gs,) + shape[-1:],
                             fan_in ** -0.5 / 7.0, jnp.float32)
            return packed, scale
        # dequant std ~= 73 * scale ~= 0.57 * fan_in^-0.5: same magnitude
        # class as the bf16 init; quality is irrelevant for random weights.
        q = jax.random.randint(key, shape, -127, 128, jnp.int8)
        scale = jnp.full(shape[:-2] + shape[-1:], fan_in ** -0.5 / 127.0,
                         jnp.float32)
        return q, scale

    layers: Params = {
        "input_norm": jnp.ones((L, d), dtype),
        "post_attn_norm": jnp.ones((L, d), dtype),
    }
    for name, shape, fan in (("wq", (L, d, nh * hd), d),
                             ("wk", (L, d, nkv * hd), d),
                             ("wv", (L, d, nkv * hd), d),
                             ("wo", (L, nh * hd, d), nh * hd)):
        layers[name], layers[name + "_scale"] = wq8(next(keys), shape, fan)
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, nh * hd), dtype)
        layers["bk"] = jnp.zeros((L, nkv * hd), dtype)
        layers["bv"] = jnp.zeros((L, nkv * hd), dtype)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, hd), dtype)
        layers["k_norm"] = jnp.ones((L, hd), dtype)
    mlp_shapes = [("w_gate", (L, E, d, ff) if cfg.is_moe else (L, d, ff), d),
                  ("w_up", (L, E, d, ff) if cfg.is_moe else (L, d, ff), d),
                  ("w_down", (L, E, ff, d) if cfg.is_moe else (L, ff, d), ff)]
    if not cfg.is_moe and cfg.mlp_type == "mlp":
        mlp_shapes = mlp_shapes[1:]
    if cfg.is_moe:
        layers["router"] = w(next(keys), (L, d, E), d)
    for name, shape, fan in mlp_shapes:
        layers[name], layers[name + "_scale"] = wq8(next(keys), shape, fan)
    _add_opt_extras(cfg, layers, dtype)

    params: Params = {
        "embed": w(next(keys), (cfg.vocab_size, d), d),
        "final_norm": jnp.ones((d,), dtype),
        "layers": layers,
    }
    if cfg.norm_type == "layernorm":
        params["final_norm_b"] = jnp.zeros((d,), dtype)
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = w(next(keys), (cfg.max_model_len + 2, d), d)
    if not cfg.tie_word_embeddings:
        params["lm_head"], params["lm_head_scale"] = wq8(
            next(keys), (d, cfg.vocab_size), d)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array,
               eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) * (xf - mu), axis=-1, keepdims=True)
    y = ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return y * weight + bias


def _norm(cfg: ModelConfig, x: jax.Array, store: Params,
          name: str) -> jax.Array:
    """Config-dispatched normalization: llama-class RMSNorm or OPT-class
    LayerNorm (with bias, stored as ``<name>_b``). norm_type is static
    config, so the branch resolves at trace time."""
    if cfg.norm_type == "layernorm":
        return layer_norm(x, store[name], store[name + "_b"],
                          cfg.rms_norm_eps)
    return rms_norm(x, store[name], cfg.rms_norm_eps)


def _embed(params: Params, cfg: ModelConfig, tokens: jax.Array,
           positions: jax.Array) -> jax.Array:
    """Token embedding lookup, plus OPT-class learned positional embeddings
    (HF OPTLearnedPositionalEmbedding keeps a +2 offset into the table)."""
    h = params["embed"][tokens]
    if cfg.pos_embedding == "learned":
        h = h + params["pos_embed"][positions + 2]
    return h


def _dot(x: jax.Array, lp: Params, name: str,
         use_pallas: Optional[bool] = None) -> jax.Array:
    """x @ lp[name] in f32, transparently handling the quant ladder
    (ops/quant.py) — this is the ONE sanctioned consumer of quantized
    weights (pinned by the KGCT009 quant-surface lint rule):

    - int8 (per-output-channel scale): the int8->bf16 convert fuses into
      the dot (weights stream from HBM at half the bytes) and the scale
      applies as one [out]-vector multiply on the f32 result.
    - int4 (packed nibbles + group scales, ``scale.ndim == w.ndim``): the
      dequant-fused matmul contracts per input group and folds the scales
      into the f32 partials — no dequantized weight copy in HBM
      (ops.quant.int4_matmul; Pallas kernel on TPU).
    - dense-precision weights take the plain path.
    """
    w = lp[name]
    if w.dtype == jnp.int8:
        scale = lp[name + "_scale"]
        if quant_ops.is_packed_int4(w, scale):
            return quant_ops.int4_matmul(x, w, scale, use_pallas=use_pallas)
        out = jnp.dot(x, w.astype(x.dtype), preferred_element_type=jnp.float32)
        return out * scale
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


# HF ACT2FN["gelu"] is the exact erf GELU; jax.nn.gelu defaults to the tanh
# approximation, which accumulates ~1e-3 activation error per layer and
# breaks HF-parity tolerances.
_MLP_ACTS = {"relu": jax.nn.relu,
             "gelu": functools.partial(jax.nn.gelu, approximate=False),
             "gelu_new": jax.nn.gelu,   # HF's tanh-approximated variant
             "silu": jax.nn.silu}


def _dense_mlp(lp: Params, x: jax.Array, cfg: ModelConfig,
               tp_axis: Optional[str] = None,
               use_pallas: Optional[bool] = None) -> jax.Array:
    """Megatron MLP: gate/up column-sharded, down row-sharded. Under GSPMD
    (tp_axis=None) the psum is inserted by the partitioner; inside shard_map
    (parallel/pp.py) ``tp_axis`` names the manual mesh axis to reduce over.
    ``mlp_type="mlp"`` is the OPT-class fc1/act/fc2 block (w_up/w_down with
    biases, no gate); biases add AFTER the down-projection reduce so they
    are applied exactly once under tp."""
    if cfg.mlp_type == "mlp":
        h = _dot(x, lp, "w_up", use_pallas)
        if "b_up" in lp:
            h = h + lp["b_up"]
        h = _MLP_ACTS[cfg.mlp_act](h).astype(x.dtype)
        out = _dot(h, lp, "w_down", use_pallas)
        if tp_axis is not None:
            out = jax.lax.psum(out, tp_axis)
        if "b_down" in lp:
            out = out + lp["b_down"]
        return out.astype(x.dtype)
    gate = _dot(x, lp, "w_gate", use_pallas)
    up = _dot(x, lp, "w_up", use_pallas)
    h = (jax.nn.silu(gate) * up).astype(x.dtype)
    out = _dot(h, lp, "w_down", use_pallas)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out.astype(x.dtype)


def _moe_mlp(lp: Params, x: jax.Array, cfg: ModelConfig,
             tp_axis: Optional[str] = None,
             ep_axis: Optional[str] = None,
             use_pallas: Optional[bool] = None) -> jax.Array:
    """Mixtral-style sparse MoE, dense-dispatch formulation: every expert runs
    over all tokens; combine weights zero out non-routed pairs. Exact (no
    capacity drops) and shard-friendly: under expert parallelism each device
    evaluates its local experts and the combine reduces over the expert axis —
    a psum over ``ep`` (automatic under GSPMD since the combine einsum
    contracts E; explicit when ``ep_axis`` names a manual shard_map axis).
    T is small in the serving hot loop, so the extra FLOPs stay MXU-bound
    rather than latency-critical."""
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    # Router always sees the full expert set (router weights replicated).
    router_logits = jnp.dot(x.astype(jnp.float32), lp["router"].astype(jnp.float32))
    topk_vals, topk_idx = jax.lax.top_k(router_logits, k)           # [T, k]
    topk_w = jax.nn.softmax(topk_vals, axis=-1)                      # [T, k]
    # [T, k, E] one-hot routing -> [T, E] combine weights.
    combine = jnp.sum(jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)
                      * topk_w[..., None], axis=1)
    E_local = lp["w_gate"].shape[0]  # E under GSPMD; E/ep inside shard_map
    if ep_axis is not None and E_local != E:
        start = jax.lax.axis_index(ep_axis) * E_local
        combine = jax.lax.dynamic_slice_in_dim(combine, start, E_local, axis=1)

    def expert_fn(ep_params):
        gate = _dot(x, ep_params, "w_gate", use_pallas)
        up = _dot(x, ep_params, "w_up", use_pallas)
        h = (jax.nn.silu(gate) * up).astype(x.dtype)
        return _dot(h, ep_params, "w_down", use_pallas)              # [T, d]

    expert_params = {k: lp[k] for k in
                     ("w_gate", "w_up", "w_down",
                      "w_gate_scale", "w_up_scale", "w_down_scale")
                     if k in lp}
    expert_outs = jax.vmap(expert_fn)(expert_params)  # [E_local, T, d]
    out = jnp.einsum("te,etd->td", combine, expert_outs)
    reduce_axes = tuple(a for a in (ep_axis, tp_axis) if a is not None)
    if reduce_axes:
        out = jax.lax.psum(out, reduce_axes)
    return out.astype(x.dtype)


def _qkv(lp: Params, cfg: ModelConfig, x: jax.Array, positions: jax.Array,
         use_pallas: Optional[bool] = None):
    """Project + per-head norm (qwen3) + RoPE. x: [T, d] -> q [T,nh,hd], k/v [T,nkv,hd].
    Head counts are derived from the projection widths (not cfg) so the same
    code runs on tp-local shards inside shard_map (parallel/pp.py)."""
    T = x.shape[0]
    q = _dot(x, lp, "wq", use_pallas)
    k = _dot(x, lp, "wk", use_pallas)
    v = _dot(x, lp, "wv", use_pallas)
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.astype(x.dtype).reshape(T, q.shape[-1] // cfg.head_dim, cfg.head_dim)
    k = k.astype(x.dtype).reshape(T, k.shape[-1] // cfg.head_dim, cfg.head_dim)
    v = v.astype(x.dtype).reshape(T, v.shape[-1] // cfg.head_dim, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    if cfg.pos_embedding == "rope":
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                scaling=cfg.rope_scaling_dict)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    return q, k, v


def _mlp_block(lp: Params, cfg: ModelConfig, x: jax.Array,
               tp_axis: Optional[str] = None,
               ep_axis: Optional[str] = None,
               use_pallas: Optional[bool] = None) -> jax.Array:
    if cfg.is_moe:
        return _moe_mlp(lp, x, cfg, tp_axis=tp_axis, ep_axis=ep_axis,
                        use_pallas=use_pallas)
    return _dense_mlp(lp, x, cfg, tp_axis=tp_axis, use_pallas=use_pallas)


# ---------------------------------------------------------------------------
# Forward passes (scan over stacked layers; attn addresses the pool by index)
# ---------------------------------------------------------------------------

def _layer_scan(params: Params, cfg: ModelConfig, h: jax.Array,
                positions: jax.Array, attn_fn,
                layer_slice=None,
                tp_axis: Optional[str] = None,
                ep_axis: Optional[str] = None,
                use_pallas: Optional[bool] = None,
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Scan the layer body over stacked weights.

    The KV pool does NOT travel through the scan: it is closed over whole and
    ``attn_fn`` receives the LAYER INDEX (scanned as xs) to address it.
    Slicing the pool per layer as scan xs — the previous design — made XLA
    materialize a [1, P, ps, kd] copy of each layer's pool every layer every
    substep (~1.4 ms/substep, ~20% of decode, measured in the round-3 device
    trace); the Pallas kernel addresses the stacked pool with a dynamic layer
    index instead, moving zero pool bytes. Each layer's freshly projected
    K/V come out as scan ys, and the caller commits them to the pool in ONE
    donated scatter after the scan (ops.attention.write_kv_pages_all).
    Threading the pool through the scan as carry/ys would force a full pool
    copy per step.

    attn_fn(lp, q, k, v, layer_idx) -> attn_out, where the pool holds tokens
    written in PREVIOUS steps only (attention folds the current step's k/v in
    directly).

    ``layer_slice`` restricts to a contiguous [start, stop) layer range.
    ``tp_axis``/``ep_axis`` name manual mesh axes when running inside
    shard_map (parallel/pp.py); under GSPMD they stay None and the SPMD
    partitioner inserts the equivalent collectives.

    Returns (h, k_all, v_all) with k_all/v_all: [L, T, n_kv_local, hd].
    """
    layers = params["layers"]
    if layer_slice is not None:
        start, stop = layer_slice
        layers = jax.tree.map(lambda a: a[start:stop], layers)

    def body(h, xs):
        lp, layer_idx = xs
        resid = h
        x = _norm(cfg, h, lp, "input_norm")
        q, k, v = _qkv(lp, cfg, x, positions, use_pallas)
        attn_out = attn_fn(lp, q, k, v, layer_idx)
        attn_out = attn_out.reshape(x.shape[0], -1)
        o = _dot(attn_out, lp, "wo", use_pallas)
        if tp_axis is not None:  # row-sharded wo: partial sums over local heads
            o = jax.lax.psum(o, tp_axis)
        if "bo" in lp:           # after the reduce: applied exactly once
            o = o + lp["bo"]
        h = resid + o.astype(h.dtype)
        resid = h
        x = _norm(cfg, h, lp, "post_attn_norm")
        h = resid + _mlp_block(lp, cfg, x, tp_axis=tp_axis, ep_axis=ep_axis,
                               use_pallas=use_pallas)
        return h, (k, v)

    n_layers = jax.tree.leaves(layers)[0].shape[0]
    h, (k_all, v_all) = jax.lax.scan(
        body, h, (layers, jnp.arange(n_layers, dtype=jnp.int32)))
    return h, k_all, v_all


def forward_prefill(params: Params, cfg: ModelConfig, tokens: jax.Array,
                    meta: PrefillMeta, kv: KVCache,
                    layer_slice=None, use_pallas=None,
                    hidden_in: Optional[jax.Array] = None,
                    tp_axis: Optional[str] = None,
                    ep_axis: Optional[str] = None,
                    attn_mesh=None, attn_impl=None):
    """Ragged prefill over T flattened tokens. Returns (selected_hidden [B, d],
    new_kv, raw_hidden [T, d]). ``hidden_in`` replaces the embedding lookup for
    non-first pipeline stages; ``raw_hidden`` is what rotates stage-to-stage.
    ``attn_mesh``: under a GSPMD mesh, run the Pallas attention per-shard via
    shard_map over the tp axis (ops.attention.ragged_prefill_attention_tp).
    ``attn_impl``: full override ``fn(q, k, v, seg_ids, positions) -> out``
    (the engine passes ring attention here for sp>1 meshes)."""
    scale = cfg.head_dim ** -0.5
    h = (_embed(params, cfg, tokens, meta.positions)
         if hidden_in is None else hidden_in)

    def attn_fn(lp, q, k, v, layer_idx):
        # Prefill attends within the in-batch k/v only (each sequence's whole
        # prompt is in this batch); the pool is written post-scan for decode.
        if attn_impl is not None:
            return attn_impl(q, k, v, meta.seg_ids, meta.positions)
        if attn_mesh is not None:
            return ragged_prefill_attention_tp(attn_mesh, q, k, v,
                                               meta.seg_ids, meta.positions,
                                               scale)
        return ragged_prefill_attention(q, k, v, meta.seg_ids, meta.positions,
                                        scale, use_pallas=use_pallas)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  layer_slice, tp_axis=tp_axis,
                                  ep_axis=ep_axis, use_pallas=use_pallas)
    if layer_slice is not None:
        kv = KVCache(k=kv.k[layer_slice[0]:layer_slice[1]],
                     v=kv.v[layer_slice[0]:layer_slice[1]])
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    selected = h[meta.logits_indices]
    return _norm(cfg, selected, params, "final_norm"), new_kv, h


def forward_prefill_hist(params: Params, cfg: ModelConfig, tokens: jax.Array,
                         meta: PrefillMeta, kv: KVCache,
                         page_table: jax.Array, hist_len: jax.Array,
                         use_pallas=None, attn_mesh=None,
                         hidden_in: Optional[jax.Array] = None,
                         tp_axis: Optional[str] = None,
                         ep_axis: Optional[str] = None):
    """Chunked prefill: one sequence's chunk attending to its pool history +
    itself causally (ops.attention.prefill_history_attention). Returns
    (normed_selected [1, d], new_kv, raw_hidden [T, d]). ``attn_mesh``: under
    a GSPMD mesh, run the Pallas history kernel per-shard via shard_map over
    the tp axis. ``hidden_in``/``tp_axis``/``ep_axis``: manual-mesh entry for
    non-first pipeline stages (parallel/pp.py's pipelined chunked prefill)."""
    scale = cfg.head_dim ** -0.5
    h = (_embed(params, cfg, tokens, meta.positions)
         if hidden_in is None else hidden_in)

    def attn_fn(lp, q, k, v, layer_idx):
        if attn_mesh is not None:
            return prefill_history_attention_tp(
                attn_mesh, q, k, v, meta.seg_ids, meta.positions, kv.k, kv.v,
                page_table, hist_len, scale, layer=layer_idx)
        return prefill_history_attention(
            q, k, v, meta.seg_ids, meta.positions, kv.k, kv.v,
            page_table, hist_len, scale, layer=layer_idx,
            use_pallas=use_pallas)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  tp_axis=tp_axis, ep_axis=ep_axis,
                                  use_pallas=use_pallas)
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    selected = h[meta.logits_indices]
    return _norm(cfg, selected, params, "final_norm"), new_kv, h


def forward_mixed(params: Params, cfg: ModelConfig, tokens: jax.Array,
                  meta: MixedMeta, kv: KVCache,
                  use_pallas=None, use_pallas_hist=None, attn_mesh=None):
    """Mixed prefill/decode step (stall-free batching): ONE forward over the
    combined token axis — embedding, QKV/MLP matmuls and norms run once for
    chunk and decode tokens together, so the weight streaming a decode step
    pays is amortized over the prefill chunk riding along — with attention
    split at the static chunk/decode boundary: chunk tokens run history
    attention against their own pool pages, decode rows run paged decode
    (ops.attention.mixed_attention). Returns (normed_selected [R_pad, d],
    new_kv, raw_hidden [T, d]).

    Single-mesh and GSPMD-tp regimes only — under pp the layer stack is
    sharded outside this path and under sp ring attention replaces the
    ragged kernels; the engine falls back to the legacy scheduler policy
    there."""
    scale = cfg.head_dim ** -0.5
    h = _embed(params, cfg, tokens, meta.positions)
    n_prefill = tokens.shape[0] - meta.page_tables.shape[0]

    def attn_fn(lp, q, k, v, layer_idx):
        return mixed_attention(
            q, k, v, meta.seg_ids, meta.positions, kv.k, kv.v,
            meta.chunk_page_table, meta.hist_len, meta.page_tables,
            meta.context_lens, scale, n_prefill=n_prefill, layer=layer_idx,
            use_pallas=use_pallas, use_pallas_hist=use_pallas_hist,
            attn_mesh=attn_mesh)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  use_pallas=use_pallas)
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    selected = h[meta.logits_indices]
    return _norm(cfg, selected, params, "final_norm"), new_kv, h


def forward_spec_mixed(params: Params, cfg: ModelConfig, tokens: jax.Array,
                       meta: MixedMeta, kv: KVCache, S: int,
                       use_pallas=None, use_pallas_hist=None,
                       attn_mesh=None):
    """Spec×mixed step: ONE forward over the combined
    ``[prefill chunk | verify slices]`` token axis — embedding, QKV/MLP
    matmuls and norms run once for chunk and verify tokens together (the
    weight streaming a verify step pays is amortized over the chunk riding
    along, the same economics that motivated mixed batching) — with
    attention split at the static chunk/verify boundary
    (ops.attention.spec_mixed_attention). ``S = k+1`` is config-static per
    compiled shape (the engine passes it as a jit static arg):
    ``n_prefill = T - R_pad * S``. Returns (normed_selected
    [R_pad*S + 1, d] — every verify slot plus the chunk's last token —
    new_kv, raw_hidden [T, d])."""
    scale = cfg.head_dim ** -0.5
    h = _embed(params, cfg, tokens, meta.positions)
    n_prefill = tokens.shape[0] - meta.page_tables.shape[0] * S

    def attn_fn(lp, q, k, v, layer_idx):
        return spec_mixed_attention(
            q, k, v, meta.seg_ids, meta.positions, kv.k, kv.v,
            meta.chunk_page_table, meta.hist_len, meta.page_tables,
            meta.context_lens, scale, n_prefill=n_prefill, layer=layer_idx,
            use_pallas=use_pallas, use_pallas_hist=use_pallas_hist,
            attn_mesh=attn_mesh)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  use_pallas=use_pallas)
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    selected = h[meta.logits_indices]
    return _norm(cfg, selected, params, "final_norm"), new_kv, h


def forward_spec_verify(params: Params, cfg: ModelConfig, tokens: jax.Array,
                        meta: SpecMeta, kv: KVCache, use_pallas=None):
    """Speculative-verification forward: ONE program scores every running
    sequence's k drafted tokens. Embedding, QKV/MLP matmuls and norms run
    over the flat ``[R_pad * S]`` token axis (the weight streaming a decode
    step pays is amortized over all draft positions — the same economics
    as mixed batching); attention runs the batched draft-verification
    shape (ops.attention.spec_verify_attention: paged-pool history + an
    S x S causal block per row). Returns (normed_hidden [T, d] over EVERY
    slot — the verifier needs logits at all draft positions, not one
    sampled row — new_kv, raw_hidden [T, d]). All new K/V (including
    drafts that will be rejected) commit in the one post-scan scatter;
    rejected slots sit past the sequence's committed length and are
    overwritten before any later step reads them."""
    scale = cfg.head_dim ** -0.5
    h = _embed(params, cfg, tokens, meta.positions)

    def attn_fn(lp, q, k, v, layer_idx):
        return spec_verify_attention(
            q, k, v, kv.k, kv.v, meta.page_tables, meta.context_lens, scale,
            layer=layer_idx, use_pallas=use_pallas)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  use_pallas=use_pallas)
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    return _norm(cfg, h, params, "final_norm"), new_kv, h


def forward_decode(params: Params, cfg: ModelConfig, tokens: jax.Array,
                   meta: DecodeMeta, kv: KVCache,
                   layer_slice=None, use_pallas=None,
                   hidden_in: Optional[jax.Array] = None,
                   tp_axis: Optional[str] = None,
                   ep_axis: Optional[str] = None,
                   attn_mesh=None):
    """Decode step: B sequences, one new token each, against the paged pool.
    Returns (normed_hidden [B, d], new_kv, raw_hidden [B, d]).
    ``attn_mesh``: under a GSPMD mesh, run the Pallas attention per-shard via
    shard_map over the tp axis (ops.attention.paged_decode_attention_tp)."""
    scale = cfg.head_dim ** -0.5
    h = (_embed(params, cfg, tokens, meta.positions)
         if hidden_in is None else hidden_in)

    if layer_slice is not None:
        kv = KVCache(k=kv.k[layer_slice[0]:layer_slice[1]],
                     v=kv.v[layer_slice[0]:layer_slice[1]])

    def attn_fn(lp, q, k, v, layer_idx):
        # Pool holds positions 0..ctx-2; this step's k/v fold in directly and
        # are committed to the pool in one post-scan scatter. The STACKED pool
        # + dynamic layer index go straight to the kernel — no per-layer pool
        # slice is ever materialized (see _layer_scan docstring).
        if attn_mesh is not None:
            return paged_decode_attention_tp(attn_mesh, q, kv.k, kv.v,
                                             meta.page_tables,
                                             meta.context_lens, k, v, scale,
                                             layer=layer_idx)
        return paged_decode_attention(q, kv.k, kv.v, meta.page_tables,
                                      meta.context_lens, k, v, scale,
                                      layer=layer_idx, use_pallas=use_pallas)

    h, k_all, v_all = _layer_scan(params, cfg, h, meta.positions, attn_fn,
                                  layer_slice, tp_axis=tp_axis, ep_axis=ep_axis)
    new_kv = KVCache(*write_kv_pages_all(kv.k, kv.v, k_all, v_all,
                                         meta.slot_mapping))
    return _norm(cfg, h, params, "final_norm"), new_kv, h


def compute_logits(params: Params, cfg: ModelConfig, hidden: jax.Array,
                   use_pallas: Optional[bool] = None) -> jax.Array:
    """hidden [B, d] -> logits [B, V] in fp32. ``use_pallas`` reaches the
    dequant-fused int4 head matmul (same tri-state as the attention
    kernels: None = auto by backend, False = the XLA kill-switch)."""
    if cfg.tie_word_embeddings:
        return jnp.dot(hidden, params["embed"].T,
                       preferred_element_type=jnp.float32)
    return _dot(hidden, params, "lm_head", use_pallas)
