"""Sequence state tracked by the continuous-batching scheduler."""

from __future__ import annotations

import enum
import time
from typing import Optional

from .sampling_params import SamplingParams


class SequenceStatus(enum.Enum):
    WAITING = "waiting"        # queued, no KV pages yet
    RUNNING = "running"        # resident in the batch
    PREEMPTED = "preempted"    # evicted under memory pressure; resumes by
                               # swap-in (host KV tier) or recompute
    FINISHED = "finished"


class FinishReason(enum.Enum):
    STOP = "stop"              # hit EOS / stop token
    LENGTH = "length"          # hit max_tokens or max_model_len
    ABORT = "abort"            # client cancelled
    MIGRATE = "migrated"       # live-migrated to a peer replica (drain):
                               # the stream continues elsewhere; locally the
                               # sequence is terminal without a client-facing
                               # finish


class Sequence:
    """One request's generation state. Pages are owned by the scheduler's
    PageAllocator; this object just records which pages back it."""

    def __init__(self, request_id: str, prompt_token_ids: list[int],
                 params: SamplingParams, eos_token_id: Optional[int] = None):
        self.request_id = request_id
        self.prompt_token_ids = list(prompt_token_ids)
        self.output_token_ids: list[int] = []
        self.output_logprobs: list[float] = []
        self.output_top_logprobs: list[list] = []   # [(token_id, lp) x N]
        self.params = params
        self.eos_token_id = eos_token_id
        self.status = SequenceStatus.WAITING
        self.finish_reason: Optional[FinishReason] = None
        self.pages: list[int] = []
        # Two-tier KV cache: host-pool page ids holding this sequence's
        # committed KV while it is preempted-by-swap (engine/kv_cache).
        self.host_pages: list[int] = []
        self.arrival_time = time.monotonic()
        self.first_token_time: Optional[float] = None  # for TTFT metrics
        # Disaggregated import: the decode-replica-observed TTFT (remote
        # prefill + KV transfer + import). step() never sees the first-token
        # transition for an imported sequence — append_token stamps
        # first_token_time at import — so TTFT-based accounting (histogram,
        # SLO attainment/goodput gate) must use this span, not
        # first_token_time - arrival_time (which would read ~0).
        self.handoff_ttft_s: Optional[float] = None
        # Lifecycle timestamps/counters for the observability layer: first
        # scheduling (queue-wait), terminal time (e2e latency; also the
        # idempotence guard for Observability.on_finish), preemption count
        # (outcome labeling + preempt/resume trace events).
        self.scheduled_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.preempt_count = 0
        # Chunked prefill progress: tokens whose KV is already committed to
        # the pool by earlier chunks. Reset on preemption (pages are freed,
        # the prompt recomputes from scratch).
        self.num_prefilled = 0
        # Prefix-cache lookup done (one per (re)admission — a blocked head is
        # rescheduled many times and must not re-hash/re-fork per call).
        self.prefix_checked = False
        # Disaggregated prefill/decode: a prefill-replica request whose
        # committed KV must survive its finish so the export seam can ship
        # it to a decode replica (scheduler.finish parks it in
        # ``scheduler.held`` instead of releasing; aborts still release).
        self.hold_kv = False

    @property
    def all_token_ids(self) -> list[int]:
        """Prompt + generated tokens — everything whose KV must be resident.
        This is what a recompute-prefill replays after preemption."""
        return self.prompt_token_ids + self.output_token_ids

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_output_tokens(self) -> int:
        return len(self.output_token_ids)

    @property
    def num_tokens(self) -> int:
        return self.num_prompt_tokens + self.num_output_tokens

    def last_window_pos(self, next_input_pos: int, window: int,
                        max_len: int) -> int:
        """Highest position a decode window starting its inputs at
        ``next_input_pos`` can touch, clamped to the model cap AND this
        request's own max_tokens budget. Window-tail tokens past either
        bound route to the scrap page, so page growth sized by this bound
        makes EXACTLY-sized pools safe (no pages a request can never use).
        The single source of truth for scheduler._schedule_decode and the
        speculative chain's engine._advance_window."""
        return min(next_input_pos + window - 1, max_len - 1,
                   self.num_prompt_tokens + self.params.max_tokens - 1)

    @property
    def is_finished(self) -> bool:
        return self.status == SequenceStatus.FINISHED

    def append_token(self, token_id: int,
                     logprob: Optional[float] = None,
                     top: Optional[list] = None) -> None:
        if self.first_token_time is None:
            self.first_token_time = time.monotonic()
        self.output_token_ids.append(token_id)
        if logprob is not None:
            self.output_logprobs.append(logprob)
        if top is not None:
            self.output_top_logprobs.append(top)

    def check_stop(self, max_model_len: int) -> Optional[FinishReason]:
        """Token-level stop conditions (string-level stops are handled by the
        server layer which owns the tokenizer)."""
        if not self.output_token_ids:
            return None
        last = self.output_token_ids[-1]
        if not self.params.ignore_eos and self.eos_token_id is not None \
                and last == self.eos_token_id:
            return FinishReason.STOP
        if last in self.params.stop_token_ids:
            return FinishReason.STOP
        if self.num_output_tokens >= self.params.max_tokens:
            return FinishReason.LENGTH
        if self.num_tokens >= max_model_len:
            return FinishReason.LENGTH
        return None

    def __repr__(self):
        return (f"Sequence({self.request_id}, status={self.status.value}, "
                f"prompt={self.num_prompt_tokens}, out={self.num_output_tokens})")
